"""Continuous-batching engine tests (CPU, tiny model).

The load-bearing property: a request decoded by the slot-based engine —
whatever else is in flight, whenever it was admitted — produces exactly the
tokens the one-shot sampler produces for the same prompt under greedy
decoding. Everything else (slot reuse, mid-flight admission, streaming
order) is scaffolding on top of that invariant.
"""

import json
import time

import jax
import jax.numpy as jnp
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.models.sampler import generate
from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineRequest, bucket_for


CONFIG = get_config("tiny-test")
PARAMS = init_params(jax.random.PRNGKey(0), CONFIG, dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _default_pipeline_env(monkeypatch):
    """Pin the engine's env-driven defaults: an ambient PRIME_SERVE_OVERLAP=0
    (someone debugging with the escape hatch) or PRIME_SERVE_WARMUP=1 must
    not silently flip every engine test onto the other code path."""
    monkeypatch.delenv("PRIME_SERVE_OVERLAP", raising=False)
    monkeypatch.delenv("PRIME_SERVE_WARMUP", raising=False)
    monkeypatch.delenv("PRIME_SERVE_MESH", raising=False)
    monkeypatch.delenv("PRIME_SERVE_SPEC", raising=False)
    monkeypatch.delenv("PRIME_SERVE_DRAFT_LEN", raising=False)
    monkeypatch.delenv("PRIME_SERVE_PREFIX_CACHE_MB", raising=False)
    monkeypatch.delenv("PRIME_SERVE_PREFIX_CACHE_HOST_MB", raising=False)


def reference_tokens(prompt_ids: list[int], n: int) -> list[int]:
    """One-shot greedy generation for a single prompt via the sampler."""
    prompts = jnp.asarray([prompt_ids], dtype=jnp.int32)
    lengths = jnp.asarray([len(prompt_ids)], dtype=jnp.int32)
    result = generate(
        PARAMS, prompts, lengths, CONFIG, jax.random.PRNGKey(7),
        max_new_tokens=n, temperature=0.0,
    )
    return result.tokens[0].tolist()


def make_engine(**kw) -> ContinuousBatchingEngine:
    kw.setdefault("max_slots", 4)
    kw.setdefault("capacity", 128)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefix_cache_mb", 0)  # prefix tests opt in explicitly
    return ContinuousBatchingEngine(PARAMS, CONFIG, **kw)


def drain(engine, *requests, max_ticks=200):
    for _ in range(max_ticks):
        engine.tick()
        if all(r.done for r in requests):
            return
    raise AssertionError("requests did not finish")


def test_bucket_for():
    assert bucket_for(1, 2048) == 16
    assert bucket_for(16, 2048) == 16
    assert bucket_for(17, 2048) == 32
    assert bucket_for(100, 2048) == 128
    assert bucket_for(100, 100) == 100
    with pytest.raises(ValueError):
        bucket_for(300, 128)


def test_single_request_matches_one_shot_sampler():
    prompt = [5, 9, 301, 42, 77]
    engine = make_engine()
    req = engine.submit(prompt, max_new_tokens=12)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, 12)


def test_concurrent_requests_each_match_reference():
    prompts = [[3, 1, 4, 1, 5], [2, 7, 18], [161, 80, 33, 98, 226, 50], [101]]
    engine = make_engine()
    reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
    drain(engine, *reqs)
    for p, r in zip(prompts, reqs):
        assert r.all_tokens(timeout=1) == reference_tokens(p, 10)


def test_batched_admission_mixed_plans_match_reference():
    """A burst whose prompts span different row buckets: the same-plan
    groups batch, the odd one goes alone, and every request still emits the
    one-shot sampler's exact greedy tokens."""
    short = [[3, 1, 4], [2, 7, 18, 9], [11, 12]]            # one bucket
    long = [list(range(2, 40))]                              # bigger bucket
    engine = make_engine()
    reqs = [engine.submit(p, max_new_tokens=8) for p in short + long]
    drain(engine, *reqs)
    for p, r in zip(short + long, reqs):
        assert r.all_tokens(timeout=1) == reference_tokens(p, 8)


def test_batched_admission_with_prefix_hit_in_burst():
    """A burst containing a prompt that prefix-hits the cache routes that
    request through the seeded single path while the rest batch; tokens
    still match the reference for all of them."""
    base = list(range(5, 37))  # 32 tokens: above min_prefix, bucket-aligned
    engine = make_engine(prefix_cache_mb=64)
    warm = engine.submit(base + [7], max_new_tokens=4)
    drain(engine, warm)
    # burst: one prefix-hitting prompt + two cold ones
    prompts = [base + [9, 3], [41, 42, 43], [91, 92, 93, 94]]
    reqs = [engine.submit(p, max_new_tokens=8) for p in prompts]
    drain(engine, *reqs)
    assert engine.prefix_hits >= 1
    for p, r in zip(prompts, reqs):
        assert r.all_tokens(timeout=1) == reference_tokens(p, 8)


def test_engine_stats_counters():
    """stats() tracks admissions (batched + single), completions, tokens,
    and the batched-wave count."""
    engine = make_engine()
    prompts = [[3, 1, 4], [2, 7, 18], [9, 9, 9], [5, 6]]
    reqs = [engine.submit(p, max_new_tokens=6) for p in prompts]
    drain(engine, *reqs)
    s = engine.stats()
    assert s["requests_admitted"] == 4
    assert s["requests_completed"] == 4
    assert s["tokens_emitted"] == sum(len(r.all_tokens(timeout=1)) for r in reqs)
    assert s["batched_admission_waves"] >= 1  # the 4-wide cold wave
    assert s["active_slots"] == 0
    assert s["queue_depth"] == 0
    assert s["uptime_s"] >= 0


def test_batched_admission_seeds_prefix_cache():
    """A batched wave stores its first member's staged row, so a recurring
    shared-prefix burst prefix-hits from the second wave on (and the hit
    path still emits reference tokens)."""
    base = list(range(5, 37))  # 32 tokens, bucket-aligned, above min_prefix
    engine = make_engine(prefix_cache_mb=64)
    wave1 = [engine.submit(base + [t], max_new_tokens=4) for t in (101, 102)]
    drain(engine, *wave1)
    assert engine.prefix_hits == 0
    wave2 = [engine.submit(base + [t], max_new_tokens=4) for t in (103, 104)]
    drain(engine, *wave2)
    assert engine.prefix_hits >= 1
    for t, r in zip((103, 104), wave2):
        assert r.all_tokens(timeout=1) == reference_tokens(base + [t], 4)


def test_mid_flight_admission():
    """A request admitted while another is mid-decode: both match reference."""
    engine = make_engine()
    first = engine.submit([11, 22, 33], max_new_tokens=16)
    engine.tick()  # admit + one chunk
    engine.tick()  # another chunk, mid-flight
    second = engine.submit([44, 55], max_new_tokens=8)
    drain(engine, first, second)
    assert first.all_tokens(timeout=1) == reference_tokens([11, 22, 33], 16)
    assert second.all_tokens(timeout=1) == reference_tokens([44, 55], 8)


def test_slot_reuse_oversubscription():
    """More requests than slots: later ones wait, slots are reused, and every
    request still matches the reference."""
    engine = make_engine(max_slots=2)
    prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
    reqs = [engine.submit(p, max_new_tokens=6) for p in prompts]
    drain(engine, *reqs)
    for p, r in zip(prompts, reqs):
        assert r.all_tokens(timeout=1) == reference_tokens(p, 6)


def test_eos_stops_emission():
    prompt = [5, 9, 301, 42, 77]
    ref = reference_tokens(prompt, 12)
    eos = ref[3]  # pretend the 4th generated token is EOS
    engine = make_engine(eos_id=eos)
    req = engine.submit(prompt, max_new_tokens=12)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == ref[:3]


def test_max_new_tokens_one():
    prompt = [7, 8, 9]
    engine = make_engine()
    req = engine.submit(prompt, max_new_tokens=1)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, 1)


def test_per_request_sampling_params_are_traced():
    """Mixed greedy + sampled requests share the compiled decode program and
    the sampled request actually varies with temperature."""
    engine = make_engine()
    greedy = engine.submit([3, 1, 4, 1, 5], max_new_tokens=8, temperature=0.0)
    hot = engine.submit([3, 1, 4, 1, 5], max_new_tokens=8, temperature=5.0, top_p=0.9)
    drain(engine, greedy, hot)
    assert greedy.all_tokens(timeout=1) == reference_tokens([3, 1, 4, 1, 5], 8)
    assert len(hot.all_tokens(timeout=1)) == 8  # sampled path emitted fully


def test_submit_validation():
    engine = make_engine(capacity=64)
    with pytest.raises(ValueError):
        engine.submit([], max_new_tokens=4)
    with pytest.raises(ValueError):
        engine.submit(list(range(60)), max_new_tokens=10)


def test_background_thread_lifecycle():
    """start()/shutdown() drive requests without manual ticking."""
    prompt = [10, 20, 30]
    with make_engine() as engine:
        req = engine.submit(prompt, max_new_tokens=6)
        assert req.all_tokens(timeout=60) == reference_tokens(prompt, 6)


def test_row_capacity_for_non_pow2_slot_capacity():
    """A non-power-of-two slot capacity must not let a chunk overflow the
    staging row (dynamic_update_slice would clamp the write while the
    attention mask assumed the true offset — silent KV corruption)."""
    from prime_tpu.serve.engine import chunk_plan, row_capacity_for

    row = row_capacity_for(2500, 512, 3000)
    assert row == 2560  # multiple of the chunk, not bucket_for's min(pow2, cap)
    for off, size in chunk_plan(0, 2500, 512, row):
        assert off + size <= row
    with pytest.raises(ValueError, match="staging row"):
        row_capacity_for(2800, 512, 3000)  # needs 3072 > capacity: clear error


def test_request_timeout_cancels():
    engine = make_engine()
    req = engine.submit([1, 2, 3], max_new_tokens=8)  # never ticked
    with pytest.raises(TimeoutError, match="cancelled"):
        req.all_tokens(timeout=0.05)
    assert req.cancelled
    engine.tick()  # the cancelled request must not be admitted
    assert not any(engine._active)


def test_chunk_plan_invariants():
    from prime_tpu.serve.engine import MIN_BUCKET, chunk_plan

    for start, length, pc, row_cb in [
        (0, 100, 512, 128), (0, 600, 512, 1024), (16, 116, 512, 128),
        (112, 128, 512, 128), (48, 1500, 256, 2048), (0, 16, 16, 16),
    ]:
        plan = chunk_plan(start, length, pc, row_cb)
        covered = start
        for off, size in plan:
            assert off == covered, "chunks must be contiguous"
            assert size & (size - 1) == 0 and size >= 1, "power-of-two sizes"
            assert off % size == 0 or off == 0, "aligned to own size"
            assert off + size <= row_cb, "never past the row (no DUS clamping)"
            assert size <= pc
            covered = off + size
        assert covered >= length, "plan must cover the prompt"
    with pytest.raises(ValueError):
        chunk_plan(MIN_BUCKET - 1, 100, 512, 128)


def test_long_prompt_chunked_admission_matches_reference():
    """A prompt longer than prefill_chunk admits in chunks and still decodes
    token-exactly like the one-shot sampler."""
    prompt = [(i * 7) % 500 + 1 for i in range(70)]
    engine = make_engine(capacity=128, prefill_chunk=32)
    req = engine.submit(prompt, max_new_tokens=8)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, 8)


def test_prefix_cache_hit_matches_cold_path():
    """Two prompts sharing a long prefix: the second admission seeds from the
    cached row (prefix_hits increments) and produces exactly the cold-path
    tokens."""
    shared = [(i * 11) % 500 + 1 for i in range(48)]
    a = shared + [7, 8, 9]
    b = shared + [100, 200]
    engine = make_engine(capacity=128, prefill_chunk=32, min_prefix=16,
                         prefix_cache_mb=64)
    ra = engine.submit(a, max_new_tokens=6)
    drain(engine, ra)
    assert engine.prefix_hits == 0
    rb = engine.submit(b, max_new_tokens=6)
    drain(engine, rb)
    assert engine.prefix_hits == 1
    assert ra.all_tokens(timeout=1) == reference_tokens(a, 6)
    assert rb.all_tokens(timeout=1) == reference_tokens(b, 6)


def test_prefix_cache_byte_budget_evicts_lru_and_identical_prompt():
    """Byte-budget LRU: with room for ~2 stored prefixes, storing a third
    evicts the LEAST RECENTLY USED one (p1 was touched by a hit, so p2
    goes); an identical-prompt re-admission still seeds from its own
    blocks."""
    engine = make_engine(capacity=64, prefill_chunk=32, min_prefix=16,
                         prefix_cache_mb=64)
    cache = engine.prefix_cache
    p1, p2 = [1] * 20, [2] * 20
    for p in (p1, p2):
        r = engine.submit(list(p), max_new_tokens=2)
        drain(engine, r)
    per_entry = cache.bytes // 2
    assert per_entry > 0 and cache.nodes == 2
    # touch p1 (a hit refreshes its LRU stamp), then shrink the budget so a
    # third entry forces exactly one eviction
    r = engine.submit(list(p1), max_new_tokens=2)
    drain(engine, r)
    assert engine.prefix_hits == 1
    cache.budget_bytes = int(per_entry * 2.5)
    r = engine.submit([3] * 20, max_new_tokens=2)
    drain(engine, r)
    assert cache.evictions == 1 and cache.bytes <= cache.budget_bytes
    assert engine._prefix_match_len([1] * 20) == 16  # p1 survived (recently used)
    assert engine._prefix_match_len([2] * 20) == 0   # p2 was the LRU victim
    assert engine.stats()["prefix_evictions"] == 1
    # identical prompt re-admission: seeded from its own cached blocks
    r = engine.submit([3] * 20, max_new_tokens=4)
    drain(engine, r)
    assert engine.prefix_hits == 2
    assert r.all_tokens(timeout=1) == reference_tokens([3] * 20, 4)


def test_prefix_cache_partial_hit_and_block_dedup():
    """The radix upgrade over the flat list: two prompts sharing only a
    32-token preamble store that preamble ONCE (bytes grow by the divergent
    tail only), and a third prompt sharing nothing but the preamble still
    hits at preamble length."""
    pre = [(i * 13) % 400 + 1 for i in range(32)]
    a = pre + [7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22]
    b = pre + [107, 108, 109, 110, 111, 112, 113, 114, 115, 116, 117, 118,
               119, 120, 121, 122]
    engine = make_engine(capacity=128, prefill_chunk=32, min_prefix=16,
                         prefix_cache_mb=64)
    cache = engine.prefix_cache
    ra = engine.submit(list(a), max_new_tokens=4)
    drain(engine, ra)
    bytes_a = cache.bytes  # 48 stored slots
    rb = engine.submit(list(b), max_new_tokens=4)
    drain(engine, rb)
    # b hit the shared 32 tokens and stored only its 16-token tail: bytes are
    # 48 + 16 slots, NOT the 96 two full-row duplicates would cost
    assert engine.prefix_hits == 1
    assert cache.dedup_tokens >= 32
    assert cache.bytes == bytes_a * 64 // 48
    c = pre + [999, 998]
    assert engine._prefix_match_len(c) == 32  # preamble-only partial hit
    rc = engine.submit(list(c), max_new_tokens=4)
    drain(engine, rc)
    assert engine.prefix_hits == 2
    hit_hist = engine.registry.get("serve_prefix_hit_tokens").series_snapshot(tier="device")
    assert hit_hist["count"] == 2 and hit_hist["sum"] == 64.0  # 32 + 32
    for p, r in ((a, ra), (b, rb), (c, rc)):
        assert r.all_tokens(timeout=1) == reference_tokens(list(p), 4)


def test_prefix_cache_refcount_blocks_eviction():
    """A pinned match (segments mid-assembly) survives a byte-budget sweep;
    releasing the pin makes the path evictable again."""
    engine = make_engine(capacity=64, prefill_chunk=32, min_prefix=16,
                         prefix_cache_mb=64)
    cache = engine.prefix_cache
    prompt = list(range(40, 60))
    r = engine.submit(list(prompt), max_new_tokens=2)
    drain(engine, r)
    match = cache.match(prompt, limit=16)
    assert match is not None and match.length == 16
    cache.budget_bytes = 1  # everything must go — except the pinned path
    assert cache.evict_to_budget() == 0
    assert cache.bytes > 0 and cache.nodes == 1
    cache.release(match)
    assert cache.evict_to_budget() == 1
    assert cache.bytes == 0 and cache.nodes == 0


@pytest.mark.parametrize("overlap", [True, False], ids=["overlap", "sync"])
def test_prefix_cache_bit_identity_on_off(overlap):
    """Greedy outputs are bit-identical with the prefix cache disabled,
    device-only, and two-tier under device-budget pressure (segments spill
    to host RAM and hits re-upload), across the overlap and synchronous
    loops — neither the radix cache/assemble path nor the spill tier may be
    visible in the emitted tokens. (CI runs this matrix as the serve-engine
    smoke step.)"""
    pre = [(i * 19) % 300 + 2 for i in range(32)]
    alt = [(i * 23) % 300 + 2 for i in range(32)]  # disjoint preamble
    prompts = [
        pre + [7, 8, 9],
        pre + [100, 200],          # shares the full preamble with the first
        pre[:16] + [5, 5, 5, 5],   # shares only the first block
        [9, 8, 7],                 # no shared prefix at all
        alt + [1, 2],              # new preamble: under pressure, spills pre
        pre + [7, 8, 9],           # identical replay: re-uploads from host
    ]
    configs = {
        "off": dict(prefix_cache_mb=0),
        "device": dict(prefix_cache_mb=64),
        "host": dict(prefix_cache_mb=64, prefix_cache_host_mb=64),
    }
    outs = {}
    for name, kw in configs.items():
        engine = make_engine(capacity=128, prefill_chunk=32, min_prefix=16,
                             overlap=overlap, **kw)
        assert engine.overlap is overlap
        outs[name] = []
        for i, p in enumerate(prompts):
            req = engine.submit(list(p), max_new_tokens=8)
            drain(engine, req)
            outs[name].append(req.all_tokens(timeout=1))
            if name == "host" and i == 0:
                # squeeze the device budget to exactly the first stored
                # prefix: the alt-preamble store must demote, the replay
                # must re-upload (max(...,1): 0 would mean unbounded)
                engine.prefix_cache.budget_bytes = max(engine.prefix_cache.bytes, 1)
        if name != "off":
            assert engine.prefix_hits >= 3  # 2nd, 3rd, and replay prompts hit
        if name == "host":
            cache = engine.prefix_cache
            assert cache.spills > 0, "device pressure never spilled"
            assert cache.reuploads > 0, "replay never re-uploaded from host"
            assert cache.evictions == 0  # spill tier absorbed the pressure
            host_hist = engine.registry.get(
                "serve_prefix_hit_tokens"
            ).series_snapshot(tier="host")
            assert host_hist is not None and host_hist["count"] >= 1
    assert outs["device"] == outs["off"]
    assert outs["host"] == outs["off"]


@pytest.mark.parametrize("speculative", [False, True], ids=["plain", "spec"])
@pytest.mark.parametrize("overlap", [True, False], ids=["overlap", "sync"])
def test_paged_seeding_bit_identity_vs_copy(overlap, speculative):
    """Paged-gather hit seeding vs the contiguous assemble_row copy engine:
    greedy outputs are bit-identical across the overlap x speculative matrix.
    The paged engine must actually seed from the page pool (paged seeds
    counted, zero copy assembles) and the copy engine must keep the old
    path — the pool is pure data movement and may never show in tokens."""
    pre = [(i * 19) % 300 + 2 for i in range(32)]
    prompts = [
        pre + [7, 8, 9],
        pre + [100, 200],          # full-preamble hit
        pre[:16] + [5, 5, 5, 5],   # partial (one-block) hit
        [9, 8, 7],                 # cold, no prefix at all
        pre + [7, 8, 9],           # identical replay
    ]
    outs = {}
    for name, paged in (("paged", True), ("copy", False)):
        engine = make_engine(capacity=128, prefill_chunk=32, min_prefix=16,
                             prefix_cache_mb=64, overlap=overlap,
                             speculative=speculative, paged_prefix=paged)
        assert engine.paged_prefix is paged
        outs[name] = []
        for p in prompts:
            req = engine.submit(list(p), max_new_tokens=8)
            drain(engine, req)
            outs[name].append(req.all_tokens(timeout=1))
        assert engine.prefix_hits >= 3
        stats = engine.stats()
        if paged:
            assert stats["prefix_paged_seeds"] >= 3
            assert stats["prefix_assembles"] == 0
        else:
            assert stats["prefix_paged_seeds"] == 0
            assert stats["prefix_assembles"] >= 3
        hist = engine.registry.get("serve_prefix_seed_seconds").series_snapshot(
            path="paged" if paged else "copy"
        )
        assert hist is not None and hist["count"] >= 3
    assert outs["paged"] == outs["copy"]


def test_paged_seeding_interpret_kernel_bit_identity(monkeypatch):
    """The same paged seeding run through the actual pallas gather kernel
    (interpret mode on CPU) instead of the XLA gather fallback — outputs
    stay bit-identical to the copy engine (CI's kernels leg pins this)."""
    pre = [(i * 19) % 300 + 2 for i in range(32)]
    prompts = [pre + [7, 8, 9], pre + [100, 200], pre + [7, 8, 9]]

    def run(paged):
        engine = make_engine(capacity=128, prefill_chunk=32, min_prefix=16,
                             prefix_cache_mb=64, paged_prefix=paged)
        out = []
        for p in prompts:
            req = engine.submit(list(p), max_new_tokens=8)
            drain(engine, req)
            out.append(req.all_tokens(timeout=1))
        return engine, out

    copy_engine, copy_out = run(False)
    monkeypatch.setenv("PRIME_TPU_PALLAS_INTERPRET", "1")
    paged_engine, paged_out = run(True)
    assert paged_engine.stats()["prefix_paged_seeds"] >= 2
    assert paged_out == copy_out


def test_paged_prefix_gating(monkeypatch):
    """paged_prefix requires a prefix cache and a single device; the
    PRIME_SERVE_PAGED_PREFIX env knob and the kwarg both gate it off."""

    class _FakeMesh:
        size = 8

    assert make_engine(prefix_cache_mb=1).paged_prefix is True
    assert make_engine(prefix_cache_mb=0).paged_prefix is False
    assert make_engine(prefix_cache_mb=1, mesh=_FakeMesh()).paged_prefix is False
    assert make_engine(prefix_cache_mb=1, paged_prefix=False).paged_prefix is False
    monkeypatch.setenv("PRIME_SERVE_PAGED_PREFIX", "0")
    assert make_engine(prefix_cache_mb=1).paged_prefix is False
    monkeypatch.delenv("PRIME_SERVE_PAGED_PREFIX")
    assert make_engine(prefix_cache_mb=1, paged_prefix=True).paged_prefix is True


def test_prefix_cache_host_env_wiring(monkeypatch):
    """PRIME_SERVE_PREFIX_CACHE_HOST_MB and the kwarg both reach the cache as
    a host byte budget with the engine's real tier converters installed; the
    kwarg wins over the env, and the default is single-tier (0)."""
    assert make_engine(prefix_cache_mb=1).prefix_cache.host_budget_bytes == 0
    monkeypatch.setenv("PRIME_SERVE_PREFIX_CACHE_HOST_MB", "8")
    cache = make_engine(prefix_cache_mb=1).prefix_cache
    assert cache.host_budget_bytes == 8 * 2**20
    from prime_tpu.serve.engine import _segment_to_device, _segment_to_host
    assert cache._to_host is _segment_to_host
    assert cache._to_device is _segment_to_device
    kwarg = make_engine(prefix_cache_mb=1, prefix_cache_host_mb=2).prefix_cache
    assert kwarg.host_budget_bytes == 2 * 2**20

    class _FakeMesh:  # spill converters are not sharding-preserving yet
        size = 8

    with pytest.warns(UserWarning, match="host spill tier"):
        gated = make_engine(prefix_cache_mb=1, prefix_cache_host_mb=2, mesh=_FakeMesh())
    assert gated.prefix_cache.host_budget_bytes == 0
    assert gated.prefix_cache_host_mb == 0.0


def test_stats_snapshot_is_loop_ticked():
    """With the engine loop running, stats() serves the end-of-tick snapshot
    (one writer: the engine thread) instead of reading live state; a
    synchronous owner still gets a fresh computation."""
    engine = make_engine()
    fresh = engine.stats()  # no thread: computed live
    assert fresh["requests_admitted"] == 0
    with engine:
        req = engine.submit([1, 2, 3], max_new_tokens=4)
        req.all_tokens(timeout=120)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap = engine.stats()
            if snap["requests_completed"] == 1 and snap["active_slots"] == 0:
                break
            time.sleep(0.01)
        assert snap["requests_completed"] == 1
        assert snap["requests_admitted"] == 1
        # the reader got the published snapshot, not a mid-tick recomputation
        assert engine._stats_snapshot is not None


# -- speculative continuous decoding ------------------------------------------


def test_spec_engine_greedy_matches_plain():
    """The load-bearing invariant, speculative edition: whatever the drafts
    do, a greedy request emits exactly the plain engine's tokens."""
    prompts = [
        list(range(1, 9)) * 2,           # periodic: drafts land
        [7, 100, 23, 451, 88, 3],        # aperiodic: drafts mostly miss
    ]
    refs = [reference_tokens(p, 12) for p in prompts]
    engine = make_engine(speculative=True, draft_len=4)
    reqs = [engine.submit(p, max_new_tokens=12) for p in prompts]
    for req in reqs:
        drain(engine, req)
    for req, ref in zip(reqs, refs):
        assert req.all_tokens(timeout=1) == ref


def test_spec_engine_eos_and_budget():
    prompt = [5, 9, 301, 42, 77]
    ref = reference_tokens(prompt, 12)
    eos = ref[3]
    engine = make_engine(speculative=True, eos_id=eos)
    req = engine.submit(prompt, max_new_tokens=12)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == ref[:3]
    # budget: exactly max_new_tokens even when a verify run overshoots
    engine2 = make_engine(speculative=True)
    req2 = engine2.submit(list(range(1, 9)) * 2, max_new_tokens=5)
    drain(engine2, req2)
    assert len(req2.all_tokens(timeout=1)) == 5


def test_spec_engine_mixed_sampling_slots():
    """A sampled request and a greedy request decode concurrently through
    the one verify program; the greedy one still matches the reference."""
    greedy_prompt = list(range(1, 9)) * 2
    ref = reference_tokens(greedy_prompt, 10)
    engine = make_engine(speculative=True)
    sampled = engine.submit([3, 1, 4, 1, 5, 9], max_new_tokens=10, temperature=0.8, top_p=0.9)
    greedy = engine.submit(greedy_prompt, max_new_tokens=10)
    drain(engine, sampled)
    drain(engine, greedy)
    assert greedy.all_tokens(timeout=1) == ref
    assert len(sampled.all_tokens(timeout=1)) == 10


def test_spec_engine_with_kv_quant():
    prompt = list(range(1, 9)) * 2
    plain = make_engine(kv_quant=True)
    ref_req = plain.submit(prompt, max_new_tokens=10)
    drain(plain, ref_req)
    spec = make_engine(kv_quant=True, speculative=True)
    req = spec.submit(prompt, max_new_tokens=10)
    drain(spec, req)
    assert req.all_tokens(timeout=1) == ref_req.all_tokens(timeout=1)


def test_spec_engine_capacity_includes_verify_window():
    engine = make_engine(speculative=True, draft_len=4, capacity=32)
    with pytest.raises(ValueError, match="verify window"):
        engine.submit(list(range(1, 17)), max_new_tokens=12)  # 16+12+5 > 32


def test_kv_quant_engine_end_to_end():
    """int8-cache engine: requests complete, decode matches the one-shot
    sampler's kv-quant decode closely (prefill differs only by the chunked
    path attending over the int8 cache), and the cache really is int8."""
    engine = make_engine(kv_quant=True)
    assert engine._cache.k.dtype == jnp.int8 and engine._cache.quantized
    prompt = [1, 5, 9, 13, 9, 5]
    req = engine.submit(prompt, max_new_tokens=10)
    while not req.done:
        engine.tick()
    got = req.all_tokens(timeout=1)
    assert len(got) == 10
    # reference: plain generate with the same quantized-cache decode
    prompts = jnp.asarray([prompt], dtype=jnp.int32)
    lengths = jnp.asarray([len(prompt)], dtype=jnp.int32)
    ref = generate(
        PARAMS, prompts, lengths, CONFIG, jax.random.PRNGKey(7),
        max_new_tokens=10, temperature=0.0, kv_quant=True,
    ).tokens[0].tolist()
    assert got == ref


def test_kv_quant_prefix_cache_roundtrip():
    """Quantized staging rows (values + scales) survive the prefix cache:
    a warm admission reuses the int8 row and still completes correctly."""
    engine = make_engine(kv_quant=True, prefix_cache_mb=64, min_prefix=8)
    shared = list(range(1, 17))  # 16-token shared prefix
    first = engine.submit(shared + [21, 22], max_new_tokens=4)
    while not first.done:
        engine.tick()
    cold = first.all_tokens(timeout=1)
    second = engine.submit(shared + [21, 22], max_new_tokens=4)
    while not second.done:
        engine.tick()
    warm = second.all_tokens(timeout=1)
    assert engine.prefix_hits >= 1
    assert warm == cold  # identical prompt, identical int8 row -> same tokens


def test_cancel_retires_slot():
    """A cancelled request frees its slot at the next tick and its consumer
    sees a clean end-of-stream."""
    engine = make_engine(max_slots=1)
    victim = engine.submit([1, 2, 3], max_new_tokens=50)
    engine.tick()  # admit + first chunk
    assert engine._active[0]
    victim.cancel()
    next_req = engine.submit([4, 5, 6], max_new_tokens=4)
    drain(engine, next_req)  # only possible if the slot was freed
    assert victim.done
    assert next_req.all_tokens(timeout=1) == reference_tokens([4, 5, 6], 4)


def test_decode_failure_fails_requests_and_recovers():
    """A raised decode dispatch must not kill the engine: in-flight requests
    error out promptly and the next request is served fresh. (Synchronous
    loop — the overlapped error path is test_overlap_decode_failure_*.)"""
    engine = make_engine(overlap=False)
    req = engine.submit([1, 2, 3], max_new_tokens=8)
    engine._admit()
    boom = [True]
    real_chunk = engine._decode_chunk

    def exploding():
        if boom[0]:
            boom[0] = False
            raise RuntimeError("chip on fire")
        real_chunk()

    engine._decode_chunk = exploding
    engine.tick()
    with pytest.raises(RuntimeError, match="chip on fire"):
        req.all_tokens(timeout=1)
    # engine state was reallocated; a new request decodes correctly
    fresh = engine.submit([7, 8, 9], max_new_tokens=4)
    drain(engine, fresh)
    assert fresh.all_tokens(timeout=1) == reference_tokens([7, 8, 9], 4)


def test_shutdown_fails_waiting_requests_promptly():
    """Shutdown must not leave clients blocked until their read timeout:
    queued requests and in-flight slots both get a prompt error."""
    engine = make_engine()
    queued = engine.submit([5, 6], max_new_tokens=4)  # never admitted
    engine.shutdown()
    with pytest.raises(RuntimeError, match="shut down"):
        queued.all_tokens(timeout=5)

    engine2 = make_engine()
    in_flight = engine2.submit([1, 2, 3], max_new_tokens=8)
    engine2._admit()  # admitted into a slot, decode never finishes
    engine2.shutdown()
    with pytest.raises(RuntimeError, match="shut down"):
        in_flight.all_tokens(timeout=5)


# -- overlapped decode pipeline -----------------------------------------------


def test_overlap_default_env_and_spec_composes(monkeypatch):
    """Overlap is on by default, PRIME_SERVE_OVERLAP=0 switches it off, and
    speculative mode now RIDES the pipeline (drafting moved on-device, so
    the old serial-loop pin — drafts needing host tokens — is gone)."""
    assert make_engine().overlap
    monkeypatch.setenv("PRIME_SERVE_OVERLAP", "0")
    assert not make_engine().overlap
    monkeypatch.setenv("PRIME_SERVE_OVERLAP", "1")
    assert make_engine(speculative=True).overlap
    assert make_engine(speculative=True, overlap=True).overlap
    monkeypatch.delenv("PRIME_SERVE_OVERLAP")
    assert not make_engine(overlap=False).overlap
    assert not make_engine(speculative=True, overlap=False).overlap


def test_spec_env_knob_wiring(monkeypatch):
    """PRIME_SERVE_SPEC / PRIME_SERVE_DRAFT_LEN drive the constructor
    defaults through the env helpers; explicit kwargs beat the env."""
    assert not make_engine().speculative
    assert make_engine().draft_len == 4
    monkeypatch.setenv("PRIME_SERVE_SPEC", "1")
    monkeypatch.setenv("PRIME_SERVE_DRAFT_LEN", "6")
    engine = make_engine()
    assert engine.speculative and engine.draft_len == 6
    assert not make_engine(speculative=False).speculative
    assert make_engine(draft_len=3).draft_len == 3
    monkeypatch.setenv("PRIME_SERVE_SPEC", "0")
    assert not make_engine().speculative


def test_overlap_dispatches_next_chunk_before_syncing_previous(monkeypatch):
    """The load-bearing pipeline property, asserted via tracer-span order:
    chunk N+1's serve.dispatch span finishes BEFORE chunk N's serve.sync
    span — i.e. the host enqueued the next chunk before it blocked for the
    previous one's tokens."""
    from prime_tpu.obs.trace import Tracer
    from prime_tpu.serve import engine as engine_mod

    tracer = Tracer(enabled=True)
    monkeypatch.setattr(engine_mod, "TRACER", tracer)
    engine = make_engine()
    req = engine.submit([5, 9, 301, 42, 77], max_new_tokens=16)
    drain(engine, req)
    engine.tick()  # drain the lookahead chunk
    order = [
        (s["name"], s["attrs"]["seq"])
        for s in tracer.drain()
        if s["name"] in ("serve.dispatch", "serve.sync")
    ]
    assert ("serve.dispatch", 1) in order and ("serve.sync", 0) in order
    # every sync of chunk N comes after the dispatch of chunk N+1 (when one
    # exists: the final drained chunk has no successor)
    for name, seq in order:
        if name == "serve.sync" and ("serve.dispatch", seq + 1) in order:
            assert order.index(("serve.dispatch", seq + 1)) < order.index(
                ("serve.sync", seq)
            ), f"chunk {seq + 1} was not dispatched before chunk {seq}'s sync"
    assert req.all_tokens(timeout=1) == reference_tokens([5, 9, 301, 42, 77], 16)


def test_overlap_greedy_streams_identical_to_sync():
    """Bit-identical token streams: the overlapped pipeline reorders host
    work, never device math — greedy decode must emit exactly what the
    synchronous loop emits, request by request."""
    prompts = [[3, 1, 4, 1, 5], [2, 7, 18], [161, 80, 33, 98, 226, 50], [101]]

    def run(overlap):
        engine = make_engine(overlap=overlap)
        reqs = [engine.submit(list(p), max_new_tokens=11) for p in prompts]
        drain(engine, *reqs)
        engine.tick()  # overlapped mode: drain the lookahead chunk
        return [r.all_tokens(timeout=1) for r in reqs]

    assert run(True) == run(False)


def test_overlap_eos_lag_counts_wasted_decode():
    """A request retiring on EOS mid-pipeline emits nothing past EOS, and
    the lookahead chunk decoded for its slot is counted as wasted decode
    (bounded at one chunk per retirement)."""
    prompt = [5, 9, 301, 42, 77]
    ref = reference_tokens(prompt, 12)
    eos = ref[3]
    engine = make_engine(eos_id=eos)
    assert engine.overlap
    req = engine.submit(prompt, max_new_tokens=12)
    drain(engine, req)
    for _ in range(3):
        engine.tick()  # drain the pipeline
    assert req.all_tokens(timeout=1) == ref[:3]  # nothing past EOS
    stats = engine.stats()
    assert stats["wasted_decode_tokens"] >= engine.chunk
    assert stats["inflight_depth"] == 0
    assert stats["host_stall_s"] <= stats["chunk_window_s"]


def test_overlap_cancel_retires_with_one_chunk_lag():
    """Cancellation under the pipeline: the slot frees at the next chunk
    boundary, its lookahead tokens are dropped (not leaked to the slot's
    next tenant), and the replacement request decodes reference-exactly."""
    engine = make_engine(max_slots=1)
    victim = engine.submit([1, 2, 3], max_new_tokens=50)
    engine.tick()  # admit
    engine.tick()  # dispatch first chunk
    assert engine._active[0] and engine._inflight
    victim.cancel()
    replacement = engine.submit([4, 5, 6], max_new_tokens=4)
    drain(engine, replacement)
    engine.tick()
    assert victim.done
    assert replacement.all_tokens(timeout=1) == reference_tokens([4, 5, 6], 4)
    assert engine.stats()["wasted_decode_tokens"] >= engine.chunk


def test_overlap_decode_failure_with_inflight_chunk_recovers():
    """A raised dispatch while a lookahead chunk is in flight: the pipeline
    is dropped, in-flight requests fail promptly (donated buffers are gone),
    device state reallocates, and the next request is served fresh."""
    engine = make_engine()
    req = engine.submit([1, 2, 3], max_new_tokens=32)
    engine.tick()  # admit
    engine.tick()  # dispatch chunk 0
    assert engine._inflight
    real_fn = engine._decode_fn

    def exploding(*args, **kwargs):
        raise RuntimeError("chip on fire")

    engine._decode_fn = exploding
    engine.tick()  # dispatch raises with a chunk still in flight
    engine._decode_fn = real_fn
    assert not engine._inflight
    with pytest.raises(RuntimeError, match="chip on fire"):
        req.all_tokens(timeout=1)
    fresh = engine.submit([7, 8, 9], max_new_tokens=4)
    drain(engine, fresh)
    assert fresh.all_tokens(timeout=1) == reference_tokens([7, 8, 9], 4)


def test_spec_serial_loop_reference(monkeypatch):
    """The serial speculative loop (overlap=False) is the bit-identity
    reference: the fused dispatch syncs immediately and never leaves a
    chunk in flight."""
    engine = make_engine(speculative=True, draft_len=4, overlap=False)
    assert not engine.overlap
    req = engine.submit(list(range(1, 9)) * 2, max_new_tokens=12)
    drain(engine, req)
    assert not engine._inflight
    assert req.all_tokens(timeout=1) == reference_tokens(list(range(1, 9)) * 2, 12)


def test_spec_overlap_pipelines_like_decode(monkeypatch):
    """The tentpole property: speculative mode rides the one-chunk-deep
    pipeline — spec chunk N+1's serve.spec_dispatch span finishes BEFORE
    chunk N's serve.sync span (the host enqueued the next fused
    propose+verify before blocking for the previous one's tokens), and the
    emitted greedy tokens still match the reference exactly."""
    from prime_tpu.obs.trace import Tracer
    from prime_tpu.serve import engine as engine_mod

    tracer = Tracer(enabled=True)
    monkeypatch.setattr(engine_mod, "TRACER", tracer)
    engine = make_engine(speculative=True, draft_len=4)
    assert engine.overlap
    prompt = [5, 9, 301, 42, 77]
    req = engine.submit(prompt, max_new_tokens=16)
    drain(engine, req)
    engine.tick()  # drain the lookahead chunk
    order = [
        (s["name"], s["attrs"]["seq"])
        for s in tracer.drain()
        if s["name"] in ("serve.spec_dispatch", "serve.sync")
    ]
    assert ("serve.spec_dispatch", 1) in order and ("serve.sync", 0) in order
    for name, seq in order:
        if name == "serve.sync" and ("serve.spec_dispatch", seq + 1) in order:
            assert order.index(("serve.spec_dispatch", seq + 1)) < order.index(
                ("serve.sync", seq)
            ), f"spec chunk {seq + 1} was not dispatched before chunk {seq}'s sync"
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, 16)


@pytest.mark.parametrize("cache_mb", [0, 8], ids=["nocache", "prefixcache"])
def test_spec_bit_identity_matrix(cache_mb):
    """The acceptance matrix: greedy outputs with speculative mode on are
    bit-identical to the serial spec loop AND to non-spec decode across
    overlap x prefix-cache, including a second shared-prefix wave that
    actually hits the cache when it is on."""
    shared = list(range(5, 37))  # 32 tokens: two MIN_BUCKET blocks
    prompts = [
        list(range(1, 9)) * 2,            # periodic: drafts land
        [7, 100, 23, 451, 88, 3],         # aperiodic: drafts mostly miss
        shared + [61, 62],                # shared-prefix pair: wave 2 hits
        shared + [63],
    ]

    def run(**kw):
        engine = make_engine(prefix_cache_mb=cache_mb, min_prefix=16, **kw)
        waves = []
        for _ in range(2):
            reqs = [engine.submit(list(p), max_new_tokens=10) for p in prompts]
            drain(engine, *reqs)
            engine.tick()  # drain any lookahead chunk
            waves.append([r.all_tokens(timeout=1) for r in reqs])
        return engine, waves

    spec_overlap, out_spec_overlap = run(speculative=True, overlap=True)
    spec_serial, out_spec_serial = run(speculative=True, overlap=False)
    plain, out_plain = run(speculative=False, overlap=True)
    assert out_spec_overlap == out_spec_serial == out_plain
    for p, tokens in zip(prompts, out_spec_overlap[0]):
        assert tokens == reference_tokens(list(p), 10)
    if cache_mb:
        # the prefix cache really served the second wave under speculation
        assert spec_overlap.prefix_hits >= 2
        assert spec_overlap.prefix_hits == spec_serial.prefix_hits == plain.prefix_hits
    # acceptance evidence flowed: periodic prompts accept drafts
    stats = spec_overlap.stats()
    assert stats["speculative"] and stats["draft_len"] == 4
    assert stats["spec_accept_ratio"] > 0


def test_spec_acceptance_metrics_and_waste_accounting():
    """Spec obs satellite: serve_spec_accepted_tokens observes per-window
    accepted drafts, serve_spec_draft_tokens_total counts proposals, the
    accept-ratio gauge publishes their quotient, and a retirement-lagged
    spec window counts its accepted-length run as wasted decode."""
    prompt = [5, 9, 301, 42, 77]
    ref = reference_tokens(prompt, 12)
    eos = ref[3]
    engine = make_engine(speculative=True, draft_len=4, eos_id=eos)
    assert engine.overlap
    req = engine.submit(prompt, max_new_tokens=12)
    drain(engine, req)
    for _ in range(3):
        engine.tick()  # drain the pipeline (stale lookahead window)
    assert req.all_tokens(timeout=1) == ref[:3]
    values = engine.registry.values()
    proposed = values["serve_spec_draft_tokens_total"]
    assert proposed > 0 and proposed % engine.draft_len == 0
    hist = engine.registry.snapshot()["serve_spec_accepted_tokens"]["series"][0]
    assert hist["count"] == proposed / engine.draft_len
    expected_ratio = hist["sum"] / proposed
    engine.stats()
    assert engine.registry.values()["serve_spec_accept_ratio"] == pytest.approx(
        expected_ratio
    )
    # the EOS-retired slot's stale in-flight window was counted as waste
    assert engine.stats()["wasted_decode_tokens"] >= 1


def test_spec_overlap_admission_overhead_capacity_pin():
    """Satellite: with an in-flight spec chunk a slot can hold up to
    2*(draft_len+1) unretired token positions, so admission reserves them —
    a request at exactly the bound completes without any KV write past the
    slot capacity, and one more token is refused at submit()."""
    engine = make_engine(speculative=True, draft_len=4, capacity=64)
    assert engine.overlap and engine.spec_overhead == 10
    fits = 64 - 16 - engine.spec_overhead
    prompt = list(range(1, 9)) * 2  # periodic 16 tokens: windows really run
    req = engine.submit(prompt, max_new_tokens=fits)
    with pytest.raises(ValueError, match="verify window"):
        engine.submit(prompt, max_new_tokens=fits + 1)
    drain(engine, req)
    for _ in range(3):
        engine.tick()  # let the stale lookahead window land
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, fits)
    import numpy as np

    # device truth: even after the stale lookahead window, no slot length
    # escapes the row — every KV write a LIVE request saw landed unclamped
    lengths = np.asarray(engine._cache.lengths)
    assert int(lengths.max()) <= engine.capacity
    # the serial loop reserves a single window
    serial = make_engine(speculative=True, draft_len=4, capacity=64, overlap=False)
    assert serial.spec_overhead == 5
    serial.submit(prompt, max_new_tokens=64 - 16 - 5)


def test_idle_burst_requeues_into_one_batched_wave():
    """The idle-path admission fix: a request popped by the idle loop is
    requeued at the FRONT (arrival order kept) and admitted through the
    batched _admit() path together with the rest of the burst — not
    prefilled singly via the old argmin path."""
    engine = make_engine()
    prompts = [[3, 1, 4], [2, 7, 18], [9, 9, 9], [5, 6]]
    reqs = [engine.submit(list(p), max_new_tokens=6) for p in prompts]
    # what _run's idle path does: pop one, requeue, tick
    first = engine._pending.get(timeout=1)
    assert first is reqs[0]
    engine._requeue(first)
    engine.tick()
    assert engine.batched_waves == 1  # ONE 4-wide wave, order preserved
    assert [engine._requests[s].id for s in sorted(engine._requests)] == [
        r.id for r in reqs
    ]
    drain(engine, *reqs)
    for p, r in zip(prompts, reqs):
        assert r.all_tokens(timeout=1) == reference_tokens(p, 6)


# -- AOT warmup ----------------------------------------------------------------


def test_warmup_compiles_programs_and_preserves_cold_state(monkeypatch):
    """warmup() executes the bounded program set (decode + every
    chunk-prefill/finalize shape) against the engine's own device state and
    leaves it indistinguishable from cold: the first real request still
    decodes reference-exactly. PRIME_SERVE_WARMUP gates the start() hook."""
    engine = make_engine(max_slots=2, capacity=32, prefill_chunk=16, warmup=True)
    assert engine.warmup_enabled
    rng_before = engine._rng
    programs = engine.warmup()
    # decode + per-(row, batch) chunk/finalize: rows {16, 32} x batches {1, 2}
    assert programs >= 1 + 2 * 2 * 2
    # cold-state indistinguishability includes the sampling stream: a warmed
    # engine's sampled requests must be bit-identical to a cold engine's
    assert (engine._rng == rng_before).all()
    stats = engine.stats()
    assert stats["warmup_programs"] == programs
    req = engine.submit([5, 9, 3], max_new_tokens=6)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == reference_tokens([5, 9, 3], 6)
    # warmup against a live engine would splice zero-length garbage over
    # occupied slots: guarded
    busy = engine.submit([7, 8], max_new_tokens=20)
    engine.tick()
    assert any(engine._active)
    with pytest.raises(RuntimeError, match="idle engine"):
        engine.warmup()
    busy.cancel()

    monkeypatch.setenv("PRIME_SERVE_WARMUP", "1")
    assert make_engine().warmup_enabled
    monkeypatch.setenv("PRIME_SERVE_WARMUP", "0")
    assert not make_engine().warmup_enabled


def test_warmup_failure_reallocates_state_and_serves():
    """A warmup dispatch that raises AFTER consuming its donated inputs must
    not brick the engine: _run reallocates device state and the first real
    request still decodes reference-exactly."""
    engine = make_engine(warmup=True)
    real_make = engine._make_decode
    boomed = []

    def flaky_make():
        fn = real_make()

        def wrapper(*args, **kwargs):
            out = fn(*args, **kwargs)  # donation already happened
            if not boomed:
                boomed.append(1)
                raise RuntimeError("warmup boom")
            return out

        return wrapper

    engine._make_decode = flaky_make
    with engine:
        req = engine.submit([1, 2, 3], max_new_tokens=4)
        assert req.all_tokens(timeout=120) == reference_tokens([1, 2, 3], 4)
    assert boomed


def test_warmup_speculative_covers_spec_program_set():
    """The spec program set is pinned relative to the plain engine: one
    fused propose+verify program plus one history-seed program per
    admission-wave width (powers of two up to max_slots). A drifting count
    means a spec program real traffic compiles mid-pipeline that warmup
    missed. Warmup must also leave the history ring cold: the first real
    request still matches the reference."""
    kw = dict(max_slots=2, capacity=64, prefill_chunk=16)
    engine = make_engine(speculative=True, draft_len=4, **kw)
    programs = engine.warmup()
    plain_programs = make_engine(**kw).warmup()
    # + fused spec dispatch + hist-seed at wave widths {1, 2}
    assert programs == plain_programs + 1 + 2
    import numpy as np

    assert int(np.asarray(engine._hist_len).max()) == 0  # ring is cold
    prompt = list(range(1, 9)) * 2
    req = engine.submit(prompt, max_new_tokens=10)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, 10)


def test_stats_reports_pipeline_fields():
    engine = make_engine()
    req = engine.submit([1, 2, 3], max_new_tokens=6)
    drain(engine, req)
    engine.tick()
    s = engine.stats()
    assert s["overlap"] is True
    assert s["inflight_depth"] == 0
    assert s["chunk_window_s"] > 0
    assert 0.0 <= s["overlap_ratio"] <= 1.0
    assert s["host_stall_s"] >= 0
    assert s["wasted_decode_tokens"] >= 0 and s["warmup_programs"] == 0


def test_engine_backend_server_integration():
    """EngineBackend behind InferenceServer: concurrent non-stream requests
    and true live SSE streaming, token deltas matching the reference."""
    import httpx

    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.serve import InferenceServer
    from prime_tpu.serve.engine import EngineBackend

    tok = ByteTokenizer()
    with make_engine(capacity=128) as engine:
        backend = EngineBackend(engine, tok)
        with InferenceServer("tiny-test", backend, port=0) as srv:
            # non-streaming
            r = httpx.post(
                f"{srv.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "ab"}], "max_tokens": 8},
                timeout=60,
            )
            assert r.status_code == 200
            body = r.json()["choices"][0]["message"]["content"]

            # live streaming of the same prompt: identical final text
            streamed = ""
            with httpx.stream(
                "POST",
                f"{srv.url}/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "ab"}],
                    "max_tokens": 8,
                    "stream": True,
                },
                timeout=60,
            ) as resp:
                assert resp.headers["content-type"].startswith("text/event-stream")
                for line in resp.iter_lines():
                    if not line.startswith("data: ") or line == "data: [DONE]":
                        continue
                    chunk = json.loads(line[len("data: "):])
                    delta = chunk["choices"][0]["delta"]
                    streamed += delta.get("content", "")
            assert streamed == body


def test_engine_backend_generate_blocking():
    """The backend's generate() protocol (non-streaming path) detokenizes
    exactly the engine's emitted ids."""
    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.serve.engine import EngineBackend

    tok = ByteTokenizer()
    prompt = "hello"
    with make_engine(capacity=128) as engine:
        backend = EngineBackend(engine, tok)
        [text] = backend.generate([prompt], max_new_tokens=6, temperature=0.0)
    ref = reference_tokens(tok.encode(prompt), 6)
    assert text == tok.decode(ref)


def test_engine_under_mesh():
    """The engine runs sharded over a device mesh (tp over kv heads).
    No capability gate: the engine's dispatch sites enter the mesh via
    parallel.compat.enter_mesh, which falls back to the Mesh context
    manager on pre-set_mesh jax builds."""
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import cache_spec, shard_params

    mesh = make_mesh({"dp": 1, "fsdp": 1, "tp": 2}, devices=jax.devices()[:2])
    sharded = shard_params(PARAMS, mesh, CONFIG)
    # no outer jax.set_mesh: the engine must enter the mesh context itself
    # (its background thread would not inherit a caller's context manager)
    engine = ContinuousBatchingEngine(
        sharded, CONFIG, max_slots=2, capacity=64, chunk=4,
        mesh=mesh, cache_spec=cache_spec(),
    )
    prompt = [9, 8, 7, 6]
    req = engine.submit(prompt, max_new_tokens=6)
    drain(engine, req)
    assert req.all_tokens(timeout=1) == reference_tokens(prompt, 6)


def test_engine_under_sp_mesh():
    """Slot-sharded long-context serving (VERDICT r4 #7): the engine's KV
    cache slot axis shards over an sp axis (sp_cache_spec) and concurrent
    requests still decode exactly the one-shot sampler's tokens."""
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import prune_spec, shard_params, sp_cache_spec

    mesh = make_mesh({"dp": 1, "fsdp": 1, "tp": 2, "sp": 2}, devices=jax.devices()[:4])
    sharded = shard_params(PARAMS, mesh, CONFIG)
    engine = ContinuousBatchingEngine(
        sharded, CONFIG, max_slots=2, capacity=64, chunk=4,
        mesh=mesh, cache_spec=prune_spec(sp_cache_spec(), mesh),
    )
    prompts = [[9, 8, 7, 6], [5, 4, 3]]
    reqs = [engine.submit(p, max_new_tokens=6) for p in prompts]
    while not all(r.done for r in reqs):
        engine.tick()
    for p, r in zip(prompts, reqs):
        assert r.all_tokens(timeout=1) == reference_tokens(p, 6)


def test_serve_model_accepts_sequence_parallel():
    """`prime serve --sp N` reaches the engine: serve_model must accept
    sequence_parallel and build the sp-meshed continuous engine with a
    slot-sharded cache spec (this kwarg was dropped in round 4 — the CLI
    raised TypeError before any model loaded)."""
    from prime_tpu.serve import serve_model

    server = serve_model(
        "tiny-test", port=0, slice_name="v5e-8", sequence_parallel=2,
        continuous=True, max_slots=2, slot_capacity=64, chunk=4,
    )
    with server:
        engine = server.generator.engine
        assert engine.mesh is not None and engine.mesh.shape.get("sp") == 2
        assert engine.cache_spec[-1] == "sp"  # slot axis sharded
        import httpx

        response = httpx.post(
            f"{server.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "2+2="}], "max_tokens": 4},
            timeout=120,
        )
        assert response.status_code == 200


def test_device_ngram_proposals_match_backward_scan():
    """The device-resident drafter (propose_ngram_drafts over the history
    ring — the one the fused spec program calls) must propose exactly what
    the O(history) host backward scan it replaced proposed, across random
    histories: most recent earlier bigram occurrence wins, fallbacks repeat
    the trailing token."""
    import random

    from prime_tpu.models.speculative import propose_ngram_drafts

    def scan_reference(history, draft_len, pad_id):
        if len(history) < 2:
            return list(history[-1:]) * draft_len
        t0, t1 = history[-2], history[-1]
        for position in range(len(history) - 3, -1, -1):
            if history[position] == t0 and history[position + 1] == t1:
                window = history[position + 2 : position + 2 + draft_len]
                # a tail-adjacent match repeats the trailing token past the
                # valid length — "the run continues", never ring pads
                return window + [t1] * (draft_len - len(window))
        return [t1] * draft_len

    rng = random.Random(7)
    width, draft_len, pad_id = 40, 4, 0
    for _ in range(40):
        # small alphabet → plenty of repeated bigrams
        history = [rng.randrange(1, 6) for _ in range(rng.randrange(1, 30))]
        ring = history + [pad_id] * (width - len(history))
        drafts = propose_ngram_drafts(
            jnp.asarray([ring], dtype=jnp.int32),
            jnp.asarray([len(history)], dtype=jnp.int32),
            draft_len,
        )
        assert drafts[0].tolist() == scan_reference(history, draft_len, pad_id)


def test_engine_gptoss_matches_sampler():
    """GPT-OSS architecture through the continuous engine: attention sinks
    and the biased clamped-GLU MoE must produce the sampler's exact greedy
    tokens through chunked prefill + slot decode."""
    config = get_config("tiny-gptoss")
    params = init_params(jax.random.PRNGKey(3), config, dtype=jnp.float32)
    prompts = [[5, 42, 100, 7, 61, 9], [17, 3, 88]]
    refs = []
    for p in prompts:
        result = generate(
            params, jnp.asarray([p], dtype=jnp.int32),
            jnp.asarray([len(p)], dtype=jnp.int32), config,
            jax.random.PRNGKey(7), max_new_tokens=10, temperature=0.0,
        )
        refs.append(result.tokens[0].tolist())
    engine = ContinuousBatchingEngine(
        params, config, pad_id=0, max_slots=2, capacity=128, chunk=4,
    )
    reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
    drain(engine, *reqs)
    for req, ref in zip(reqs, refs):
        assert req.all_tokens(timeout=1) == ref


def test_spec_engine_gptoss_matches_plain():
    """Speculative decoding on the GPT-OSS architecture: the verify window
    runs attention sinks through the chunked-prefill path — greedy tokens
    must equal the plain engine's regardless of what the drafts do."""
    config = get_config("tiny-gptoss")
    params = init_params(jax.random.PRNGKey(3), config, dtype=jnp.float32)
    prompts = [list(range(1, 9)) * 3, [7, 100, 23, 451, 88, 3]]

    def run(speculative):
        engine = ContinuousBatchingEngine(
            params, config, pad_id=0, max_slots=2, capacity=128, chunk=4,
            speculative=speculative, draft_len=4,
        )
        reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
        drain(engine, *reqs)
        return [r.all_tokens(timeout=1) for r in reqs]

    assert run(False) == run(True)


def test_engine_gemma_style_window_softcap_matches_sampler():
    """Alternating sliding-window + score softcap (Gemma2 physics) through
    the continuous engine: chunked prefill and slot decode must reproduce
    the sampler's greedy tokens, incl. continuations past the window."""
    config = CONFIG.scaled(
        sliding_window=8, sliding_pattern="even", attn_softcap=30.0,
    )
    params = init_params(jax.random.PRNGKey(5), config, dtype=jnp.float32)
    prompts = [list(range(1, 20)), [7, 100, 23, 451, 88, 3]]
    refs = []
    for p in prompts:
        result = generate(
            params, jnp.asarray([p], dtype=jnp.int32),
            jnp.asarray([len(p)], dtype=jnp.int32), config,
            jax.random.PRNGKey(7), max_new_tokens=12, temperature=0.0,
        )
        refs.append(result.tokens[0].tolist())
    engine = ContinuousBatchingEngine(
        params, config, pad_id=0, max_slots=2, capacity=64, chunk=4,
    )
    reqs = [engine.submit(p, max_new_tokens=12) for p in prompts]
    drain(engine, *reqs)
    for req, ref in zip(reqs, refs):
        assert req.all_tokens(timeout=1) == ref


def test_submit_bounded_queue_raises_queue_full():
    """max_queue bounds the pending queue: submissions past it get the typed
    QueueFullError (the 429 the server maps it to carries retry_after)."""
    from prime_tpu.serve.errors import QueueFullError

    engine = make_engine(max_queue=2)
    # not started: nothing consumes the queue, so the bound is exact
    engine.submit([1, 2, 3], max_new_tokens=4)
    engine.submit([1, 2, 4], max_new_tokens=4)
    with pytest.raises(QueueFullError) as excinfo:
        engine.submit([1, 2, 5], max_new_tokens=4)
    assert excinfo.value.retry_after > 0
    assert engine.stats()["max_queue"] == 2
    # working the queue down reopens admission
    for _ in range(40):
        engine.tick()
        stats = engine.stats()
        if stats["queue_depth"] == 0 and stats["active_slots"] == 0:
            break
    engine.submit([1, 2, 6], max_new_tokens=4)


def test_drain_finishes_inflight_then_refuses_new_work():
    """drain(): in-flight requests decode to completion, new submits raise
    DrainingError, and `drained` flips once the engine is quiescent."""
    from prime_tpu.serve.errors import DrainingError

    engine = make_engine()
    req = engine.submit([1, 5, 9, 2], max_new_tokens=6)
    engine.tick()  # admit
    engine.drain()
    assert engine.stats()["state"] == "draining"
    with pytest.raises(DrainingError):
        engine.submit([1, 2, 3], max_new_tokens=4)
    drain(engine, req)  # the in-flight request still completes
    assert req.done and req.error is None
    assert len(req.all_tokens(timeout=1)) == 6
    engine.tick()  # retire the lookahead chunk
    assert engine.drained


def test_max_queue_env_default(monkeypatch):
    monkeypatch.setenv("PRIME_SERVE_MAX_QUEUE", "7")
    engine = make_engine()
    assert engine.max_queue == 7
    monkeypatch.delenv("PRIME_SERVE_MAX_QUEUE")
    assert make_engine().max_queue == 0  # unbounded by default
