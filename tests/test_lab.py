"""Lab data layer: caches, local scans, snapshot assembly, CLI view."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.lab import LabCache, LabDataSource
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


def test_cache_roundtrip_and_ttl(tmp_path):
    cache = LabCache(tmp_path, ttl_s=1000)
    assert cache.get("evals") == (None, False)
    cache.put("evals", [{"a": 1}])
    rows, fresh = cache.get("evals")
    assert rows == [{"a": 1}] and fresh

    stale_cache = LabCache(tmp_path, ttl_s=0)
    rows, fresh = stale_cache.get("evals")
    assert rows == [{"a": 1}] and not fresh  # stale rows still served

    cache.invalidate()
    assert cache.get("evals") == (None, False)


def test_local_scan_picks_up_eval_runs(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "gsm8k--llama3-8b" / "run1"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(
        json.dumps({"metrics": {"accuracy": 0.7, "num_samples": 64}})
    )
    source = LabDataSource(tmp_path)
    snap = source.snapshot()
    assert snap.local_eval_runs[0]["env"] == "gsm8k"
    assert snap.local_eval_runs[0]["accuracy"] == 0.7
    assert snap.platform["evals"] == [] and not snap.freshness["evals"]


def test_refresh_hydrates_platform_sections(tmp_path, fake):
    # seed platform state
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    from prime_tpu.api.pods import CreatePodRequest, PodsClient

    PodsClient(api).create(CreatePodRequest(name="lab-pod", slice_name="v5e-8"))

    source = LabDataSource(tmp_path, api_client=api)
    snap = source.refresh()
    assert snap.freshness["pods"] is True
    assert snap.platform["pods"][0]["name"] == "lab-pod"

    # cached snapshot works without the client
    cold = LabDataSource(tmp_path, api_client=None).snapshot()
    assert cold.platform["pods"][0]["name"] == "lab-pod"


def test_lab_view_cli(tmp_path, fake, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runner = CliRunner()
    result = runner.invoke(cli, ["lab", "sync", "--plain"])
    assert result.exit_code == 0, result.output
    assert "pods=" in result.output
    result = runner.invoke(cli, ["lab", "view", "--cached"])
    assert result.exit_code == 0, result.output
    assert "prime lab" in result.output and "Training runs" in result.output


def test_sync_surfaces_total_failure(tmp_path, fake, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("PRIME_API_KEY", "wrong-key")  # every fetch 401s
    runner = CliRunner()
    result = runner.invoke(cli, ["lab", "sync"])
    assert result.exit_code == 1
    assert "failed to sync" in result.output


def test_scan_tolerates_non_dict_metadata(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "e--m" / "bad"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text("[]")
    good = tmp_path / "outputs" / "evals" / "e--m" / "good"
    good.mkdir()
    (good / "metadata.json").write_text(json.dumps({"metrics": {"accuracy": 1.0}}))
    snap = LabDataSource(tmp_path).snapshot()
    assert [r["runId"] for r in snap.local_eval_runs] == ["good"]


def test_null_metrics_and_foreign_cache_tolerated(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "e--m" / "nullm"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(json.dumps({"metrics": None}))
    cache = LabCache(tmp_path)
    cache.directory.mkdir(parents=True, exist_ok=True)
    (cache.directory / "evals.json").write_text("[]")  # foreign cache shape
    snap = LabDataSource(tmp_path, cache=cache).snapshot()
    assert snap.local_eval_runs[0]["accuracy"] is None
    assert snap.platform["evals"] == [] and not snap.freshness["evals"]


def test_cache_tolerates_non_numeric_saved_at(tmp_path):
    cache = LabCache(tmp_path)
    cache.directory.mkdir(parents=True, exist_ok=True)
    (cache.directory / "pods.json").write_text('{"savedAt": "yesterday", "rows": [1]}')
    assert cache.get("pods") == (None, False)
