"""Lab data layer: caches, local scans, snapshot assembly, CLI view."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.lab import LabCache, LabDataSource
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


def test_cache_roundtrip_and_ttl(tmp_path):
    cache = LabCache(tmp_path, ttl_s=1000)
    assert cache.get("evals") == (None, False)
    cache.put("evals", [{"a": 1}])
    rows, fresh = cache.get("evals")
    assert rows == [{"a": 1}] and fresh

    stale_cache = LabCache(tmp_path, ttl_s=0)
    rows, fresh = stale_cache.get("evals")
    assert rows == [{"a": 1}] and not fresh  # stale rows still served

    cache.invalidate()
    assert cache.get("evals") == (None, False)


def test_local_scan_picks_up_eval_runs(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "gsm8k--llama3-8b" / "run1"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(
        json.dumps({"metrics": {"accuracy": 0.7, "num_samples": 64}})
    )
    source = LabDataSource(tmp_path)
    snap = source.snapshot()
    assert snap.local_eval_runs[0]["env"] == "gsm8k"
    assert snap.local_eval_runs[0]["accuracy"] == 0.7
    assert snap.platform["evals"] == [] and not snap.freshness["evals"]


def test_refresh_hydrates_platform_sections(tmp_path, fake):
    # seed platform state
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    from prime_tpu.api.pods import CreatePodRequest, PodsClient

    PodsClient(api).create(CreatePodRequest(name="lab-pod", slice_name="v5e-8"))

    source = LabDataSource(tmp_path, api_client=api)
    snap = source.refresh()
    assert snap.freshness["pods"] is True
    assert snap.platform["pods"][0]["name"] == "lab-pod"

    # cached snapshot works without the client
    cold = LabDataSource(tmp_path, api_client=None).snapshot()
    assert cold.platform["pods"][0]["name"] == "lab-pod"


def test_merge_rows_preserves_richer_cached_fields():
    """Progressive loading (reference snapshots.py role): a lighter incoming
    row must not wipe fields a previous fetch cached for the same id; order,
    membership, and conflicting values follow the incoming list."""
    from prime_tpu.lab.data import merge_rows

    previous = [
        {"id": "a", "status": "RUNNING", "detail": {"logs": 12}},
        {"id": "gone", "status": "DONE"},
        {"noid": True, "x": 1},
    ]
    incoming = [
        {"id": "b", "status": "PENDING"},
        {"id": "a", "status": "STOPPED"},
    ]
    merged = merge_rows(previous, incoming)
    assert [r.get("id") for r in merged] == ["b", "a"]       # incoming order, deletion propagated
    assert merged[1]["status"] == "STOPPED"                   # incoming wins conflicts
    assert merged[1]["detail"] == {"logs": 12}                # richer cached field preserved


def test_merge_rows_incoming_none_never_clobbers():
    """Fetchers dump pydantic models WITHOUT exclude_none: a lighter list
    response carries unpopulated optionals as explicit None — those must not
    wipe values a richer earlier fetch cached."""
    from prime_tpu.lab.data import merge_rows

    previous = [{"id": "a", "sshConnections": ["host1"], "note": None}]
    incoming = [{"id": "a", "sshConnections": None, "note": "fresh", "status": None}]
    merged = merge_rows(previous, incoming)
    assert merged[0]["sshConnections"] == ["host1"]   # None did not clobber
    assert merged[0]["note"] == "fresh"               # real value did win
    assert merged[0]["status"] is None                # new None field passes through


def test_refresh_survives_corrupt_cache_file(tmp_path, fake):
    """A foreign/corrupt cache file is a per-section failure recorded in
    snapshot.errors — it must not abort the other sections' refresh."""
    import json as _json

    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    from prime_tpu.api.pods import CreatePodRequest, PodsClient

    PodsClient(api).create(CreatePodRequest(name="ok-pod", slice_name="v5e-8"))
    source = LabDataSource(tmp_path, api_client=api)
    cache_dir = tmp_path / ".prime-lab" / "cache"
    cache_dir.mkdir(parents=True, exist_ok=True)
    (cache_dir / "evals.json").write_text(_json.dumps({"rows": ["not", "dicts"], "ts": 1}))
    snap = source.refresh()
    assert snap.platform["pods"][0]["name"] == "ok-pod"   # healthy section unaffected


def test_refresh_merges_into_cached_rows(tmp_path, fake):
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    from prime_tpu.api.pods import CreatePodRequest, PodsClient

    pod = PodsClient(api).create(CreatePodRequest(name="merge-pod", slice_name="v5e-8"))
    source = LabDataSource(tmp_path, api_client=api)
    source.refresh()
    # enrich the cached row as a detail hydration would
    rows, _ = source.cache.get("pods")
    rows[0]["detailNote"] = "hand-enriched"
    source.cache.put("pods", rows)
    snap = source.refresh()
    enriched = next(r for r in snap.platform["pods"] if r.get("podId") == pod.pod_id)
    assert enriched["detailNote"] == "hand-enriched"          # survived re-fetch
    assert enriched["name"] == "merge-pod"


def test_lab_view_cli(tmp_path, fake, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runner = CliRunner()
    result = runner.invoke(cli, ["lab", "sync", "--plain"])
    assert result.exit_code == 0, result.output
    assert "pods=" in result.output
    result = runner.invoke(cli, ["lab", "view", "--cached"])
    assert result.exit_code == 0, result.output
    assert "prime lab" in result.output and "Training runs" in result.output


def test_sync_surfaces_total_failure(tmp_path, fake, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("PRIME_API_KEY", "wrong-key")  # every fetch 401s
    runner = CliRunner()
    result = runner.invoke(cli, ["lab", "sync"])
    assert result.exit_code == 1
    assert "failed to sync" in result.output


def test_scan_tolerates_non_dict_metadata(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "e--m" / "bad"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text("[]")
    good = tmp_path / "outputs" / "evals" / "e--m" / "good"
    good.mkdir()
    (good / "metadata.json").write_text(json.dumps({"metrics": {"accuracy": 1.0}}))
    snap = LabDataSource(tmp_path).snapshot()
    assert [r["runId"] for r in snap.local_eval_runs] == ["good"]


def test_null_metrics_and_foreign_cache_tolerated(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "e--m" / "nullm"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(json.dumps({"metrics": None}))
    cache = LabCache(tmp_path)
    cache.directory.mkdir(parents=True, exist_ok=True)
    (cache.directory / "evals.json").write_text("[]")  # foreign cache shape
    snap = LabDataSource(tmp_path, cache=cache).snapshot()
    assert snap.local_eval_runs[0]["accuracy"] is None
    assert snap.platform["evals"] == [] and not snap.freshness["evals"]


def test_cache_tolerates_non_numeric_saved_at(tmp_path):
    cache = LabCache(tmp_path)
    cache.directory.mkdir(parents=True, exist_ok=True)
    (cache.directory / "pods.json").write_text('{"savedAt": "yesterday", "rows": [1]}')
    assert cache.get("pods") == (None, False)


# -- lab setup depth + hygiene (reference lab_setup.py / lab_hygiene.py) ------


def test_setup_generates_agent_surfaces(tmp_path):
    from prime_tpu.lab.setup import AGENT_GUIDE, setup_workspace

    report = setup_workspace(tmp_path, agents=("claude", "codex", "cursor"))
    assert (tmp_path / "CLAUDE.md").exists()
    assert (tmp_path / "AGENTS.md").exists()
    assert (tmp_path / ".cursor" / "rules" / "prime-lab.mdc").exists()
    assert (tmp_path / ".prime-lab" / "skills" / "running-evals.md").exists()
    assert "prime eval run" in (tmp_path / "CLAUDE.md").read_text()
    assert str(tmp_path / "CLAUDE.md") in report.created


def test_setup_preserves_user_content_outside_markers(tmp_path):
    from prime_tpu.lab.setup import setup_workspace

    (tmp_path / "CLAUDE.md").write_text("# My project notes\nkeep me\n")
    setup_workspace(tmp_path, agents=("claude",))
    text = (tmp_path / "CLAUDE.md").read_text()
    assert "keep me" in text and "prime-lab:begin" in text

    # editing inside the markers gets refreshed; outside survives re-setup
    mangled = text.replace("prime eval run", "BROKEN")
    (tmp_path / "CLAUDE.md").write_text(mangled + "\n# user appendix\n")
    report = setup_workspace(tmp_path, agents=("claude",))
    text = (tmp_path / "CLAUDE.md").read_text()
    assert "prime eval run" in text and "BROKEN" not in text
    assert "# user appendix" in text
    assert str(tmp_path / "CLAUDE.md") in report.updated


def test_setup_idempotent(tmp_path):
    from prime_tpu.lab.setup import setup_workspace

    setup_workspace(tmp_path)
    report = setup_workspace(tmp_path)
    assert report.created == [] and report.updated == []


def test_setup_rejects_unknown_agent(tmp_path):
    from prime_tpu.lab.setup import setup_workspace

    with pytest.raises(ValueError, match="unknown agent"):
        setup_workspace(tmp_path, agents=("emacs",))


def _git(tmp_path, *args):
    import subprocess

    subprocess.run(["git", *args], cwd=tmp_path, capture_output=True, check=True)


def test_hygiene_finds_and_fixes(tmp_path):
    from prime_tpu.lab.hygiene import apply_fixes, check_workspace

    _git(tmp_path, "init", "-q")
    (tmp_path / "id_rsa").write_text("PRIVATE KEY")
    (tmp_path / "outputs").mkdir()
    (tmp_path / "outputs" / "x.jsonl").write_text("{}")

    findings = check_workspace(tmp_path)
    codes = {f.code for f in findings}
    assert "unignored-secret" in codes and "unignored-outputs" in codes

    added = apply_fixes(tmp_path, findings)
    assert "outputs/" in added
    after = {f.code for f in check_workspace(tmp_path)}
    assert "unignored-secret" not in after and "unignored-outputs" not in after


def test_hygiene_large_file(tmp_path):
    from prime_tpu.lab.hygiene import check_workspace

    _git(tmp_path, "init", "-q")
    big = tmp_path / "model.bin"
    big.write_bytes(b"\0" * (51 * 1024 * 1024))
    findings = check_workspace(tmp_path)
    assert any(f.code == "large-file" for f in findings)


def test_hygiene_outside_git_repo(tmp_path):
    from prime_tpu.lab.hygiene import check_workspace

    findings = check_workspace(tmp_path)
    codes = {f.code for f in findings}
    assert "no-git" in codes  # informative, not an error


def test_lab_setup_and_hygiene_cli(fake, tmp_path, monkeypatch):
    runner = CliRunner()
    result = runner.invoke(
        cli, ["lab", "setup", "--dir", str(tmp_path), "--agent", "claude", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    report = json.loads(result.output)
    assert any("CLAUDE.md" in p for p in report["created"])

    _git(tmp_path, "init", "-q")
    (tmp_path / "secrets.pem").write_text("x")
    result = runner.invoke(cli, ["lab", "hygiene", "--dir", str(tmp_path), "--plain"])
    assert result.exit_code == 1  # unignored secret is an error
    assert "unignored-secret" in result.output
    result = runner.invoke(cli, ["lab", "hygiene", "--dir", str(tmp_path), "--fix", "--plain"])
    assert result.exit_code == 0, result.output


def test_hygiene_reports_every_secret_and_fix_converges(tmp_path):
    from prime_tpu.lab.hygiene import apply_fixes, check_workspace

    _git(tmp_path, "init", "-q")
    (tmp_path / "a.pem").write_text("x")
    (tmp_path / "b.pem").write_text("y")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "credentials-prod.json").write_text("{}")

    findings = check_workspace(tmp_path)
    secret_msgs = [f.message for f in findings if f.code == "unignored-secret"]
    assert len(secret_msgs) == 3  # ALL secrets reported, not one per pattern

    apply_fixes(tmp_path, findings)
    after = check_workspace(tmp_path)
    assert not any(f.code == "unignored-secret" for f in after)  # one --fix converges


def test_hygiene_ignores_git_internals(tmp_path):
    from prime_tpu.lab.hygiene import check_workspace

    _git(tmp_path, "init", "-q")
    (tmp_path / ".git" / "credentials-cache.json").write_text("{}")
    assert not any(f.code == "unignored-secret" for f in check_workspace(tmp_path))


def test_hygiene_missing_workspace_errors(fake):
    runner = CliRunner()
    result = runner.invoke(cli, ["lab", "hygiene", "--dir", "/definitely/not/a/dir"])
    assert result.exit_code != 0
    assert "does not exist" in result.output


def test_append_gitignore_handles_unterminated_file(tmp_path):
    from prime_tpu.lab.setup import append_gitignore

    (tmp_path / ".gitignore").write_text("existing-entry")  # no trailing newline
    added = append_gitignore(tmp_path, ["outputs/"])
    assert added == ["outputs/"]
    lines = (tmp_path / ".gitignore").read_text().splitlines()
    assert lines == ["existing-entry", "outputs/"]


def test_hygiene_escapes_glob_metachars_and_converges(tmp_path):
    from prime_tpu.lab.hygiene import apply_fixes, check_workspace

    _git(tmp_path, "init", "-q")
    weird = tmp_path / "data[v1].pem"
    weird.write_text("secret")
    findings = check_workspace(tmp_path)
    assert any(f.code == "unignored-secret" for f in findings)
    apply_fixes(tmp_path, findings)
    after = check_workspace(tmp_path)
    assert not any(f.code == "unignored-secret" for f in after)  # rule matched literally


# -- versioned skill bundle + surface matrix (VERDICT r2 #6) ------------------


def test_skill_bundle_refreshes_pristine_keeps_edited(tmp_path):
    """Bundle sync: a pristine skill from an older bundle refreshes on
    version bump; a locally-edited one is kept and reported skipped."""
    import json

    from prime_tpu.lab import setup as setup_mod
    from prime_tpu.lab.setup import setup_workspace

    setup_workspace(tmp_path, agents=("claude",))
    skills = tmp_path / ".prime-lab" / "skills"
    manifest = json.loads((skills / "MANIFEST.json").read_text())
    assert manifest["version"] == setup_mod.SKILLS_VERSION
    assert set(manifest["files"]) == set(setup_mod.SKILLS)

    # simulate an older pristine bundle for one skill and a local edit of another
    (skills / "running-evals.md").write_text("old bundle content\n")
    manifest["files"]["running-evals.md"] = __import__("hashlib").sha256(
        b"old bundle content\n"
    ).hexdigest()
    (skills / "MANIFEST.json").write_text(json.dumps(manifest))
    (skills / "tpu-debugging.md").write_text("MY local notes\n")

    report = setup_workspace(tmp_path, agents=("claude",))
    assert (skills / "running-evals.md").read_text() == setup_mod.SKILLS["running-evals.md"]
    assert (skills / "tpu-debugging.md").read_text() == "MY local notes\n"
    assert any("tpu-debugging.md" in s for s in report.skipped)
    # force overwrites even local edits
    setup_workspace(tmp_path, agents=("claude",), force_skills=True)
    assert (skills / "tpu-debugging.md").read_text() == setup_mod.SKILLS["tpu-debugging.md"]


def test_setup_registers_mcp_servers_additively(tmp_path):
    import json

    from prime_tpu.lab.setup import setup_workspace

    (tmp_path / ".mcp.json").write_text(
        json.dumps({"mcpServers": {"other": {"command": "x"}}})
    )
    setup_workspace(tmp_path, agents=("claude", "cursor"))
    claude_cfg = json.loads((tmp_path / ".mcp.json").read_text())
    assert claude_cfg["mcpServers"]["other"] == {"command": "x"}  # preserved
    assert claude_cfg["mcpServers"]["prime-lab"]["args"] == ["lab", "mcp"]
    cursor_cfg = json.loads((tmp_path / ".cursor" / "mcp.json").read_text())
    assert "prime-lab" in cursor_cfg["mcpServers"]
    # idempotent: second run reports unchanged, not updated
    report = setup_workspace(tmp_path, agents=("claude",))
    assert str(tmp_path / ".mcp.json") in report.unchanged


def test_setup_surface_matrix_and_hygiene_report(tmp_path):
    from prime_tpu.lab.setup import AGENT_GUIDE, setup_workspace

    report = setup_workspace(tmp_path, agents=("gemini", "windsurf"))
    assert AGENT_GUIDE.splitlines()[0] in (tmp_path / "GEMINI.md").read_text()
    assert (tmp_path / ".windsurf" / "rules" / "prime-lab.md").exists()
    assert isinstance(report.hygiene, list)  # preflight ran in the same pass
    agents_json = (tmp_path / ".prime-lab" / "agents.json").read_text()
    assert '"agents": []' in agents_json


def test_skill_bundle_downgrade_guard_and_bad_mcp_configs(tmp_path):
    import json

    from prime_tpu.lab import setup as setup_mod
    from prime_tpu.lab.setup import setup_workspace

    setup_workspace(tmp_path, agents=("claude",))
    skills = tmp_path / ".prime-lab" / "skills"
    manifest = json.loads((skills / "MANIFEST.json").read_text())
    manifest["version"] = setup_mod.SKILLS_VERSION + 5  # teammate's newer CLI
    (skills / "MANIFEST.json").write_text(json.dumps(manifest))
    (skills / "running-evals.md").write_text("newer bundle content\n")
    report = setup_workspace(tmp_path, agents=("claude",))
    assert (skills / "running-evals.md").read_text() == "newer bundle content\n"
    assert any("newer than this CLI" in s for s in report.skipped)

    # non-object configs are skipped, never overwritten or crashed on
    (tmp_path / ".mcp.json").write_text("[1, 2]")
    report = setup_workspace(tmp_path, agents=("claude",))
    assert (tmp_path / ".mcp.json").read_text() == "[1, 2]"
    assert any("not a JSON object" in s for s in report.skipped)
    (tmp_path / ".mcp.json").write_text(json.dumps({"mcpServers": None}))
    report = setup_workspace(tmp_path, agents=("claude",))
    assert json.loads((tmp_path / ".mcp.json").read_text())["mcpServers"] is None
    assert any("mcpServers is not an object" in s for s in report.skipped)


def test_lab_register_github(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    runner = CliRunner()
    result = runner.invoke(cli, ["lab", "register-github", "--dir", str(tmp_path)])
    assert result.exit_code == 0, result.output
    workflow = tmp_path / ".github" / "workflows" / "prime-lab-hygiene.yml"
    assert workflow.exists()
    text = workflow.read_text()
    assert "prime lab hygiene" in text and "pull_request" in text
    # idempotent: a rewrite leaves identical content
    assert runner.invoke(cli, ["lab", "register-github", "--dir", str(tmp_path)]).exit_code == 0
    assert workflow.read_text() == text
    # json mode reports the path
    import json as _json

    result = runner.invoke(
        cli, ["lab", "register-github", "--dir", str(tmp_path), "--output", "json"]
    )
    assert _json.loads(result.output)["path"] == str(workflow)
