"""Multi-head latent attention (DeepSeek-style MLA, models/mla.py).

The load-bearing contracts:
- the ABSORBED formulation (what serves) equals the textbook per-head
  reconstruction (the oracle) to fp32 noise;
- prefill+decode through the latent cache equals the dense no-cache forward
  at the same positions;
- the engine decodes exactly the one-shot sampler's tokens (slot splicing,
  chunked prefill, and continuous decode all ride the latent cache);
- the cache really is latent-compressed, and kv_quant is rejected loudly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_cache, init_params
from prime_tpu.models.sampler import generate

from _markers import requires_set_mesh

CFG = get_config("tiny-mla")
PARAMS = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.PRNGKey(1), (2, 12), 1, CFG.vocab_size)


@pytest.mark.parametrize("preset", ["tiny-mla", "tiny-mla-qlora"])
def test_absorbed_equals_naive_oracle(preset):
    """q_nope @ W_kc . c_kv == q_nope . (W_kc @ c_kv): the absorption is a
    reassociation, so the two formulations agree to fp32 noise."""
    from prime_tpu.models.mla import mla_attention_block, naive_mla_attention
    from prime_tpu.ops.rope import rope_frequencies

    config = get_config(preset)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()}
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 12, config.d_model)) * 0.1
    positions = jnp.broadcast_to(jnp.arange(12)[None], (2, 12))
    tables = rope_frequencies(config.qk_rope_head_dim, 64, config.rope_theta)
    absorbed, *_ = mla_attention_block(
        x, lp, positions, tables, config, None, None, None, False, "xla"
    )
    naive = naive_mla_attention(x, lp, positions, tables, config)
    assert float(jnp.max(jnp.abs(absorbed - naive))) < 1e-5


def test_prefill_decode_matches_dense(tokens):
    dense_logits, _ = forward(PARAMS, tokens, CFG)
    cache = init_cache(CFG, 2, 16, dtype=jnp.float32)
    _, cache = forward(PARAMS, tokens[:, :11], CFG, cache=cache)
    step_logits, cache = forward(
        PARAMS, tokens[:, 11:12], CFG, cache=cache, decode=True,
        positions=jnp.full((2, 1), 11, jnp.int32),
    )
    assert float(jnp.max(jnp.abs(step_logits[:, 0] - dense_logits[:, 11]))) < 1e-4
    assert cache.lengths.tolist() == [12, 12]


def test_chunked_prefill_matches_one_shot(tokens):
    """Chunked prefill writes latent columns at the offset and attends over
    the cache — logits for the final chunk must match one-shot prefill."""
    cache_ref = init_cache(CFG, 2, 16, dtype=jnp.float32)
    ref_logits, cache_ref = forward(PARAMS, tokens, CFG, cache=cache_ref)

    cache = init_cache(CFG, 2, 16, dtype=jnp.float32)
    _, cache = forward(PARAMS, tokens[:, :8], CFG, cache=cache)
    chunk_logits, cache = forward(
        PARAMS, tokens[:, 8:], CFG, cache=cache,
        prefill_offset=jnp.asarray(8, jnp.int32),
    )
    assert float(jnp.max(jnp.abs(chunk_logits - ref_logits[:, 8:]))) < 1e-4
    assert float(jnp.max(jnp.abs(cache.k - cache_ref.k))) < 1e-5


def test_generate_greedy_deterministic(tokens):
    lengths = jnp.full((2,), 12, jnp.int32)
    a = generate(PARAMS, tokens, lengths, CFG, jax.random.PRNGKey(3), max_new_tokens=6, temperature=0.0)
    b = generate(PARAMS, tokens, lengths, CFG, jax.random.PRNGKey(9), max_new_tokens=6, temperature=0.0)
    assert a.tokens.tolist() == b.tokens.tolist()  # greedy ignores the rng


def test_engine_matches_one_shot_sampler():
    from prime_tpu.serve.engine import ContinuousBatchingEngine

    prompt = [9, 8, 7, 6, 5]
    ref = generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), jnp.asarray([5], jnp.int32),
        CFG, jax.random.PRNGKey(7), max_new_tokens=6, temperature=0.0,
    ).tokens[0].tolist()
    engine = ContinuousBatchingEngine(PARAMS, CFG, max_slots=2, capacity=64, chunk=4)
    reqs = [engine.submit(prompt, max_new_tokens=6), engine.submit([3, 2], max_new_tokens=6)]
    while not all(r.done for r in reqs):
        engine.tick()
    assert reqs[0].all_tokens(timeout=1) == ref


@requires_set_mesh
def test_sharded_generate_tp_fsdp(tokens):
    """MLA under the serving mesh: query heads on tp, latent cache head axis
    replicated (cache_spec_for); decoded tokens match the single-device run."""
    from jax.sharding import NamedSharding

    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import (
        batch_spec,
        cache_spec_for,
        lengths_spec,
        prune_spec,
        shard_params,
    )

    lengths = jnp.full((2,), 12, jnp.int32)
    ref = generate(
        PARAMS, tokens, lengths, CFG, jax.random.PRNGKey(5), max_new_tokens=4,
        temperature=0.0,
    ).tokens.tolist()
    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 2}, devices=jax.devices()[:4])
    sharded = shard_params(PARAMS, mesh, CFG)
    with jax.set_mesh(mesh):
        out = generate(
            sharded,
            jax.device_put(tokens, NamedSharding(mesh, batch_spec())),
            jax.device_put(lengths, NamedSharding(mesh, lengths_spec())),
            CFG, jax.random.PRNGKey(5), max_new_tokens=4, temperature=0.0,
            attn_impl="xla", cache_spec=prune_spec(cache_spec_for(CFG), mesh),
        )
    assert out.tokens.tolist() == ref


def test_train_step_finite_grads():
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import shard_batch
    from prime_tpu.train import (
        default_optimizer,
        init_train_state,
        make_train_step,
        shard_train_state,
    )

    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 2}, devices=jax.devices()[:4])
    opt = default_optimizer()
    state = shard_train_state(
        init_train_state(init_params(jax.random.PRNGKey(3), CFG, jnp.float32), opt),
        mesh, CFG,
    )
    step = make_train_step(CFG, opt)
    t = jax.random.randint(jax.random.PRNGKey(4), (4, 16), 0, CFG.vocab_size)
    batch = tuple(
        shard_batch(x, mesh) for x in (t, jnp.roll(t, -1, 1), jnp.ones_like(t, jnp.float32))
    )
    _state, metrics = step(state, *batch)
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["grad_norm"]))


def test_cache_is_latent_compressed_and_kv_quant_rejected():
    cache = init_cache(CFG, 2, 64, dtype=jnp.float32)
    # joint latent column: rank + rope wide, ONE head; dummy v is 1-wide
    assert cache.k.shape == (CFG.n_layers, 2, 1, CFG.mla_cache_dim, 64)
    assert cache.v.shape == (CFG.n_layers, 2, 1, 1, 64)
    mha_bytes = CFG.n_layers * 2 * 2 * CFG.n_heads * (
        CFG.qk_nope_head_dim + CFG.qk_rope_head_dim
    ) * 64 * 4
    assert cache.k.nbytes + cache.v.nbytes < 0.2 * mha_bytes
    with pytest.raises(ValueError, match="kv_quant"):
        init_cache(CFG, 2, 64, quantized=True)
    with pytest.raises(ValueError, match="kv_quant"):
        generate(
            PARAMS, jnp.asarray([[1, 2]], jnp.int32), jnp.asarray([2], jnp.int32),
            CFG, jax.random.PRNGKey(0), max_new_tokens=2, kv_quant=True,
        )


def test_ring_rejected_for_mla(tokens):
    from prime_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="ring"):
        forward(PARAMS, tokens, CFG, attn_impl="ring", mesh=mesh)


def test_param_count_matches_tree():
    leaves = jax.tree_util.tree_leaves(PARAMS)
    assert sum(x.size for x in leaves) == CFG.param_count
    qcfg = get_config("tiny-mla-qlora")
    qparams = init_params(jax.random.PRNGKey(0), qcfg, dtype=jnp.float32)
    assert sum(x.size for x in jax.tree_util.tree_leaves(qparams)) == qcfg.param_count


@pytest.mark.parametrize("preset", ["tiny-mla", "tiny-mla-qlora"])
def test_int8_weights_mla(preset):
    """int8 quantization covers every MLA projection (wkv_b's scales fold
    into the absorb/value einsums exactly) and generate still runs."""
    from prime_tpu.models.quantize import is_quantized, quantize_params_int8

    config = get_config(preset)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    qparams = quantize_params_int8(params)
    assert is_quantized(qparams)
    assert isinstance(qparams["layers"]["wkv_b"], tuple)
    assert isinstance(qparams["layers"]["wkv_a"], tuple)

    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 10), 0, config.vocab_size)
    fp_logits, _ = forward(params, tokens, config)
    q_logits, _ = forward(qparams, tokens, config)
    fp_probs = np.asarray(jax.nn.softmax(fp_logits, axis=-1))
    q_probs = np.asarray(jax.nn.softmax(q_logits, axis=-1))
    assert np.abs(fp_probs - q_probs).max() < 0.06

    # scale folding is EXACT vs explicitly dequantized weights
    dequant = dict(params)
    layers = dict(qparams["layers"])
    for key, value in layers.items():
        if isinstance(value, tuple):
            layers[key] = (value[0].astype(jnp.float32) * value[1]).astype(jnp.float32)
    dequant["layers"] = layers
    d_logits, _ = forward(dequant, tokens, config)
    assert np.abs(np.asarray(q_logits) - np.asarray(d_logits)).max() < 1e-3

    out = generate(
        qparams, tokens, jnp.full((2,), 10, jnp.int32), config,
        jax.random.PRNGKey(8), max_new_tokens=4, temperature=0.0,
    )
    assert out.tokens.shape == (2, 4)


def test_int4_weights_mla_skips_wkv_b():
    """int4's reduction-axis group scales can't fold through the absorb
    einsum; wkv_b stays for the int8 pass, everything else goes int4."""
    from prime_tpu.models.quantize import quantize_params_int4, quantize_params_int8

    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    q4 = quantize_params_int8(quantize_params_int4(params))
    assert str(q4["layers"]["wq"][0].dtype) == "uint8"
    assert str(q4["layers"]["wkv_b"][0].dtype) == "int8"
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, CFG.vocab_size)
    logits, _ = forward(q4, tokens, CFG)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_unsupported_attention_features_rejected():
    """Per-head attention features have no latent-form equivalent: loud
    error, not silently different numerics."""
    bad = CFG.scaled(sliding_window=64, sliding_pattern="uniform")
    params = init_params(jax.random.PRNGKey(0), bad, dtype=jnp.float32)
    tokens = jnp.asarray([[1, 2, 3]], jnp.int32)
    with pytest.raises(ValueError, match="sliding_window"):
        forward(params, tokens, bad)
    with pytest.raises(ValueError, match="attn_softcap"):
        forward(params, tokens, CFG.scaled(attn_softcap=50.0))


# -- HF DeepSeek-V3 parity ----------------------------------------------------


@pytest.fixture(scope="module")
def deepseek_model():
    import torch
    import transformers

    cfg = transformers.DeepseekV3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        moe_intermediate_size=48,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        kv_lora_rank=32,
        q_lora_rank=48,
        qk_rope_head_dim=16,
        qk_nope_head_dim=32,
        v_head_dim=32,
        n_routed_experts=8,
        num_experts_per_tok=2,
        n_shared_experts=1,
        n_group=1,
        topk_group=1,
        first_k_dense_replace=0,
        routed_scaling_factor=2.5,
        norm_topk_prob=True,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rope_scaling=None,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(31)
    model = transformers.DeepseekV3ForCausalLM(cfg)
    model.eval()
    return model


def test_deepseek_v3_logits_match_transformers(deepseek_model):
    """The full V3 stack at once — MLA (low-rank q, interleaved rope
    de-interleaved at load), sigmoid routing with the e_score bias, routed
    scaling, shared expert — pinned against transformers' reference."""
    import torch

    from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    state = {k: v.float().numpy() for k, v in deepseek_model.state_dict().items()}
    config = config_from_hf(deepseek_model.config, name="tiny-ds-hf")
    assert config.mla and config.moe_score_func == "sigmoid"
    assert config.n_shared_experts == 1 and config.routed_scaling_factor == 2.5
    params = params_from_state_dict(
        state, config, dtype=jnp.float32,
        rope_interleave=bool(getattr(deepseek_model.config, "rope_interleave", False)),
    )
    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = deepseek_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=5e-4, atol=5e-4)


def test_deepseek_v3_greedy_matches_transformers(deepseek_model):
    import torch

    from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    state = {k: v.float().numpy() for k, v in deepseek_model.state_dict().items()}
    config = config_from_hf(deepseek_model.config, name="tiny-ds-hf")
    params = params_from_state_dict(
        state, config, dtype=jnp.float32,
        rope_interleave=bool(getattr(deepseek_model.config, "rope_interleave", False)),
    )
    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = deepseek_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()[0, 4:]
    ours = generate(
        params, jnp.asarray(prompt), jnp.asarray([4], jnp.int32), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    ).tokens[0]
    assert np.asarray(ours).tolist() == hf_out.tolist()


def test_deepseek_v3_unmodeled_features_rejected():
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "deepseek_v3"
        vocab_size = 256
        hidden_size = 64
        intermediate_size = 128
        num_hidden_layers = 2
        num_attention_heads = 4
        kv_lora_rank = 32
        q_lora_rank = None
        qk_rope_head_dim = 16
        qk_nope_head_dim = 32
        v_head_dim = 32
        n_routed_experts = 8
        first_k_dense_replace = 0
        n_group = 1
        rope_scaling = None

    ok = config_from_hf(Cfg())
    assert ok.mla and ok.q_lora_rank is None

    prefixed = Cfg()
    prefixed.first_k_dense_replace = 2
    assert config_from_hf(prefixed).first_k_dense == 2  # modeled since round 5

    grouped = Cfg()
    grouped.n_group = 4
    grouped.topk_group = 2
    cfg = config_from_hf(grouped)
    assert cfg.moe_n_groups == 4 and cfg.moe_topk_groups == 2  # modeled since r5

    scaled = Cfg()
    scaled.rope_scaling = {"type": "linear", "factor": 4}
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(scaled)  # only yarn is the published DeepSeek scheme


@pytest.fixture(scope="module")
def deepseek_prefix_model():
    """first_k_dense_replace=1: layer 0 is a dense MLP, layers 1-2 are MoE
    (the real V2-Lite/V3 structure the two-scan forward exists for)."""
    import torch
    import transformers

    cfg = transformers.DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=4, kv_lora_rank=32, q_lora_rank=48,
        qk_rope_head_dim=16, qk_nope_head_dim=32, v_head_dim=32,
        n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
        n_group=1, topk_group=1, first_k_dense_replace=1,
        routed_scaling_factor=2.5, norm_topk_prob=True,
        max_position_embeddings=128, rope_theta=10000.0, rope_scaling=None,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(33)
    model = transformers.DeepseekV3ForCausalLM(cfg)
    model.eval()
    return model


def _load_prefix(model, dtype=jnp.float32):
    from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    config = config_from_hf(model.config, name="ds-prefix")
    params = params_from_state_dict(state, config, dtype=dtype, rope_interleave=True)
    return params, config


def test_deepseek_dense_prefix_logits_match_transformers(deepseek_prefix_model):
    import torch

    params, config = _load_prefix(deepseek_prefix_model)
    assert config.first_k_dense == 1 and config.dense_ff == 128
    assert "dense_layers" in params and "router" not in params["dense_layers"]
    assert params["layers"]["router"].shape[0] == 2  # MoE tail only
    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf = deepseek_prefix_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(ours), hf, rtol=5e-4, atol=5e-4)


def test_deepseek_dense_prefix_greedy_and_engine(deepseek_prefix_model):
    """Greedy decode matches transformers through the two-scan cache, and
    the continuous engine serves the model (cache split/join per tick)."""
    import torch

    from prime_tpu.serve.engine import ContinuousBatchingEngine

    params, config = _load_prefix(deepseek_prefix_model)
    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = deepseek_prefix_model.generate(
            torch.tensor(prompt, dtype=torch.long), max_new_tokens=8,
            do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()[0, 4:]
    ours = generate(
        params, jnp.asarray(prompt), jnp.asarray([4], jnp.int32), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    ).tokens[0]
    assert np.asarray(ours).tolist() == hf_out.tolist()

    engine = ContinuousBatchingEngine(params, config, max_slots=2, capacity=64, chunk=4)
    request = engine.submit([5, 42, 100, 7], max_new_tokens=8)
    while not request.done:
        engine.tick()
    assert request.all_tokens(timeout=1) == hf_out.tolist()


def test_deepseek_dense_prefix_trains_and_quantizes(deepseek_prefix_model):
    from prime_tpu.models.quantize import quantize_params_int8
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    params, config = _load_prefix(deepseek_prefix_model)
    # quantized forward FIRST: the jitted train step donates its buffers,
    # deleting every array the q8 tree shares by reference (embed, norms)
    q8 = quantize_params_int8(params)
    assert isinstance(q8["dense_layers"]["w_gate"], tuple)  # prefix quantized too
    tokens = jnp.asarray([[3, 17, 200, 45]], jnp.int32)
    logits, _ = forward(q8, tokens, config)
    assert bool(jnp.all(jnp.isfinite(logits)))

    opt = default_optimizer()
    state = init_train_state(params, opt)
    step = make_train_step(config, opt)
    t = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, config.vocab_size)
    _state, metrics = step(state, t, jnp.roll(t, -1, 1), jnp.ones_like(t, jnp.float32))
    assert np.isfinite(float(metrics["loss"]))


def test_deepseek_v3_group_routing_matches_transformers():
    """n_group=2/topk_group=1: group-limited selection (HF's 0.0-mask quirk
    included) pinned against transformers logits + greedy."""
    import torch
    import transformers

    from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    cfg = transformers.DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, kv_lora_rank=32, q_lora_rank=None,
        qk_rope_head_dim=16, qk_nope_head_dim=32, v_head_dim=32,
        n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
        n_group=2, topk_group=1, first_k_dense_replace=0,
        routed_scaling_factor=2.5, norm_topk_prob=True,
        max_position_embeddings=128, rope_theta=10000.0, rope_scaling=None,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(35)
    model = transformers.DeepseekV3ForCausalLM(cfg)
    model.eval()
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    config = config_from_hf(model.config, name="ds-groups")
    assert config.moe_n_groups == 2 and config.moe_topk_groups == 1
    params = params_from_state_dict(state, config, dtype=jnp.float32, rope_interleave=True)
    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(ours), hf, rtol=5e-4, atol=5e-4)
    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor(prompt, dtype=torch.long), max_new_tokens=6,
            do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()[0, 4:]
    ours_gen = generate(
        params, jnp.asarray(prompt), jnp.asarray([4], jnp.int32), config,
        jax.random.PRNGKey(0), max_new_tokens=6, temperature=0.0,
    ).tokens[0]
    assert np.asarray(ours_gen).tolist() == hf_out.tolist()


def test_deepseek_v3_yarn_matches_transformers():
    """DeepSeek-yarn long-context: NTK-by-parts tables over the rope
    sub-head plus mscale_all_dim^2 on the softmax scale, pinned against
    transformers logits + greedy (the real V2/V3 checkpoints' scheme)."""
    import torch
    import transformers

    from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    cfg = transformers.DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, kv_lora_rank=32, q_lora_rank=48,
        qk_rope_head_dim=16, qk_nope_head_dim=32, v_head_dim=32,
        n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
        n_group=1, topk_group=1, first_k_dense_replace=0,
        routed_scaling_factor=2.5, norm_topk_prob=True,
        max_position_embeddings=128, rope_theta=10000.0,
        rope_scaling={
            "rope_type": "yarn", "factor": 4.0, "beta_fast": 32,
            "beta_slow": 1, "mscale": 1.0, "mscale_all_dim": 1.0,
            "original_max_position_embeddings": 32,
        },
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(37)
    model = transformers.DeepseekV3ForCausalLM(cfg)
    model.eval()
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    config = config_from_hf(model.config, name="ds-yarn")
    assert config.rope_yarn is not None
    assert config.attn_scale_mult > 1.0  # mscale_all_dim=1, factor=4 -> >1
    # no-drop capacity (E/k), as the serving path sets it: at 48 tokens the
    # default 2.0 headroom drops tokens that HF's dropless routing serves,
    # which would mask whether the YARN math matches
    config = config.scaled(capacity_factor=config.n_experts / config.experts_per_token)
    params = params_from_state_dict(state, config, dtype=jnp.float32, rope_interleave=True)
    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7] * 6], dtype=np.int32)  # past orig range
    with torch.no_grad():
        hf = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(ours), hf, rtol=5e-4, atol=5e-4)
    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor(prompt, dtype=torch.long), max_new_tokens=6,
            do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()[0, 4:]
    ours_gen = generate(
        params, jnp.asarray(prompt), jnp.asarray([4], jnp.int32), config,
        jax.random.PRNGKey(0), max_new_tokens=6, temperature=0.0,
    ).tokens[0]
    assert np.asarray(ours_gen).tolist() == hf_out.tolist()


def test_deepseek_v2_lite_preset_shapes_without_materializing():
    """The published V2-Lite architecture (15.7B, 64 experts + 2 shared, one
    dense-prefix layer) structurally checks out via eval_shape — no 15.7B
    materialization, just the traced param tree and the cache footprint."""
    config = get_config("deepseek-v2-lite")
    assert config.mla and config.first_k_dense == 1 and config.n_experts == 64
    assert config.param_count == pytest.approx(15.7e9, rel=0.02)

    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    )
    total = sum(
        int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(shapes)
    )
    assert total == config.param_count
    assert shapes["dense_layers"]["w_gate"].shape == (1, 2048, 10944)
    assert shapes["layers"]["w_gate"].shape == (26, 64, 2048, 1408)
    assert shapes["layers"]["w_shared_gate"].shape == (26, 2048, 2 * 1408)
    assert shapes["layers"]["wkv_b"].shape == (26, 512, 16 * (128 + 128))

    # latent cache: 576 * 2 bytes/token/layer -> a 32k-token sequence fits
    # in ~1 GiB of cache vs ~10.7 GiB for per-head K (nope+rope) + V (v_dim)
    cache_shapes = jax.eval_shape(
        lambda: init_cache(config, 1, 32768, dtype=jnp.bfloat16)
    )
    latent_bytes = int(np.prod(cache_shapes.k.shape)) * 2
    full_kv_bytes = 27 * 16 * ((128 + 64) + 128) * 32768 * 2
    assert latent_bytes < 0.12 * full_kv_bytes
