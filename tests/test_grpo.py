"""GRPO trainer: advantage math, masking, update direction, end-to-end reward
improvement, and a sharded update over the virtual mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from prime_tpu.evals.tokenizer import ByteTokenizer
from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.train.grpo import (
    GrpoConfig,
    group_advantages,
    make_grpo_step,
    pack_rollouts,
    run_grpo,
    token_logprobs,
)
from prime_tpu.train.trainer import init_train_state

from _markers import requires_set_mesh


@pytest.fixture()
def tiny():
    # function-scoped: make_grpo_step donates its TrainState, so params fed to
    # one step are dead buffers afterwards — each test needs a fresh tree
    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    return config, params


# -- pure math ---------------------------------------------------------------


def test_group_advantages_zero_mean_unit_std():
    rewards = np.array([[0.0, 1.0, 0.0, 1.0], [0.2, 0.4, 0.6, 0.8]], dtype=np.float32)
    adv = group_advantages(rewards, eps=0.0)
    np.testing.assert_allclose(adv.mean(axis=1), 0.0, atol=1e-6)
    np.testing.assert_allclose(adv.std(axis=1), 1.0, atol=1e-5)


def test_group_advantages_degenerate_group_is_zero():
    rewards = np.full((1, 4), 0.7, dtype=np.float32)
    adv = group_advantages(rewards)
    np.testing.assert_allclose(adv, 0.0)


def test_grpo_config_validation():
    with pytest.raises(ValueError, match="temperature"):
        GrpoConfig(temperature=0.0)
    with pytest.raises(ValueError, match="group_size"):
        GrpoConfig(group_size=1)


# -- packing -----------------------------------------------------------------


def test_pack_rollouts_contiguous_and_eos_masked():
    prompt_ids = [[5, 6, 7], [9]]
    gen = np.array([[11, 2, 0, 0], [12, 13, 14, 15]], dtype=np.int32)  # eos_id=2 row 0
    gen_lens = np.array([1, 4])
    tokens, mask = pack_rollouts(prompt_ids, gen, gen_lens, pad_id=0, total_len=8, eos_id=2)
    # row 0: prompt 5,6,7 then completion 11 + EOS 2 — both masked
    assert tokens[0].tolist() == [5, 6, 7, 11, 2, 0, 0, 0]
    assert mask[0].tolist() == [0, 0, 0, 1, 1, 0, 0, 0]
    # row 1: no EOS fired — all 4 generated tokens masked, no +1
    assert tokens[1].tolist() == [9, 12, 13, 14, 15, 0, 0, 0]
    assert mask[1].tolist() == [0, 1, 1, 1, 1, 0, 0, 0]


def test_token_logprobs_shape_and_position_zero(tiny):
    config, params = tiny
    tokens = jnp.array([[3, 4, 5, 6]], dtype=jnp.int32)
    lp = token_logprobs(params, tokens, config)
    assert lp.shape == (1, 4)
    assert float(lp[0, 0]) == 0.0
    assert bool(jnp.all(lp[:, 1:] <= 0.0))


# -- update direction --------------------------------------------------------


def test_update_raises_positive_advantage_logprob(tiny):
    """One step must raise the logprob of positively-advantaged completions
    and lower the negatively-advantaged ones — the core policy-gradient
    direction, deterministic (no sampling involved)."""
    config, params = tiny
    optimizer = optax.sgd(5e-2)
    state = init_train_state(params, optimizer)
    step = make_grpo_step(config, optimizer, clip_eps=0.2, kl_coef=0.0)

    tokens = jnp.array([[3, 4, 5, 6, 7, 8], [3, 4, 5, 9, 10, 11]], dtype=jnp.int32)
    mask = jnp.array([[0, 0, 0, 1, 1, 1], [0, 0, 0, 1, 1, 1]], dtype=jnp.float32)
    adv = jnp.array([1.0, -1.0])
    old_lp = token_logprobs(state.params, tokens, config)

    new_state, metrics = step(state, None, tokens, mask, adv, old_lp, old_lp)
    new_lp = token_logprobs(new_state.params, tokens, config)

    pos_delta = float(jnp.sum((new_lp - old_lp)[0] * mask[0]))
    neg_delta = float(jnp.sum((new_lp - old_lp)[1] * mask[1]))
    assert pos_delta > 0, f"positive-advantage completion logprob fell: {pos_delta}"
    assert neg_delta < 0, f"negative-advantage completion logprob rose: {neg_delta}"
    assert np.isfinite(float(metrics["loss"]))


def test_padding_tokens_do_not_contribute(tiny):
    """Perturbing tokens outside the mask must not change the loss."""
    config, params = tiny
    optimizer = optax.sgd(1e-2)
    state = init_train_state(params, optimizer)
    step = make_grpo_step(config, optimizer)

    tokens = jnp.array([[3, 4, 5, 6, 0, 0]], dtype=jnp.int32)
    mask = jnp.array([[0, 1, 1, 1, 0, 0]], dtype=jnp.float32)
    adv = jnp.array([1.0])
    old_lp = token_logprobs(state.params, tokens, config)
    fresh = jax.tree.map(jnp.copy, params)  # step donates its input state

    _, m1 = step(state, None, tokens, mask, adv, old_lp, old_lp)
    state2 = init_train_state(fresh, optimizer)
    tokens2 = tokens.at[0, 4].set(9)  # pad-region perturbation
    old_lp2 = jnp.where(mask > 0, old_lp, 0.0)
    _, m2 = step(state2, None, tokens2, mask, adv, old_lp2, old_lp2)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), abs=1e-5)


def test_ratio_clipping_engages(tiny):
    """With old_lp far below the current policy, ratios blow past 1+eps and
    the clip fraction must register."""
    config, params = tiny
    optimizer = optax.sgd(1e-3)
    state = init_train_state(params, optimizer)
    step = make_grpo_step(config, optimizer, clip_eps=0.2)

    tokens = jnp.array([[3, 4, 5, 6]], dtype=jnp.int32)
    mask = jnp.array([[0, 1, 1, 1]], dtype=jnp.float32)
    adv = jnp.array([1.0])
    old_lp = token_logprobs(state.params, tokens, config) - 2.0  # ratio ~ e^2
    _, metrics = step(state, None, tokens, mask, adv, old_lp, old_lp)
    assert float(metrics["clip_frac"]) == pytest.approx(1.0)
    assert float(metrics["ratio_mean"]) > 1.2


def test_kl_zero_against_self_and_positive_after_drift(tiny):
    config, params = tiny
    optimizer = optax.sgd(5e-2)
    state = init_train_state(params, optimizer)
    step = make_grpo_step(config, optimizer, kl_coef=0.1)

    tokens = jnp.array([[3, 4, 5, 6]], dtype=jnp.int32)
    mask = jnp.array([[0, 1, 1, 1]], dtype=jnp.float32)
    adv = jnp.array([1.0])
    lp0 = token_logprobs(params, tokens, config)
    new_state, metrics = step(state, None, tokens, mask, adv, lp0, lp0)
    assert float(metrics["kl"]) == pytest.approx(0.0, abs=1e-6)
    # after the update the policy has moved off the (frozen) reference
    lp1 = token_logprobs(new_state.params, tokens, config)
    state2 = init_train_state(new_state.params, optimizer)
    _, metrics2 = step(state2, None, tokens, mask, adv, lp1, lp0)
    assert float(metrics2["kl"]) > 0.0


# -- end-to-end --------------------------------------------------------------


def test_run_grpo_improves_reward():
    """20 GRPO steps on an env whose reward is the fraction of digit bytes in
    the completion (a dense, trivially learnable signal for a random-init
    model): the mean reward must rise above its start."""
    from prime_tpu.models.config import ModelConfig

    # byte-range vocab so every sampled id decodes to a real character —
    # digits carry ~16% of the random policy's mass, a dense group signal
    config = ModelConfig(
        name="grpo-test", vocab_size=64, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(1), config, dtype=jnp.float32)
    tok = ByteTokenizer()

    def scorer(completion: str, answer: str) -> float:
        if not completion:
            return 0.0
        return sum(1 for c in completion if c.isdigit()) / len(completion)

    cfg = GrpoConfig(
        group_size=4,
        prompts_per_step=2,
        max_prompt_len=8,
        max_new_tokens=8,
        temperature=1.0,
        steps=20,
        learning_rate=0.0,  # optimizer passed explicitly below
    )
    state, report = run_grpo(
        config,
        params,
        tok,
        # prompt bytes must stay under the 64-id vocab: digits/punctuation only
        examples=[{"prompt": "12+34", "answer": "1"}, {"prompt": "5*6", "answer": "2"}],
        scorer=scorer,
        cfg=cfg,
        optimizer=optax.chain(optax.clip_by_global_norm(1.0), optax.adam(3e-3)),
        rng=jax.random.PRNGKey(7),
    )
    assert report.steps == 20
    early = float(np.mean(report.mean_rewards[:3]))
    late = float(np.mean(report.mean_rewards[-3:]))
    assert late > early, f"reward did not improve: early={early:.4f} late={late:.4f}"
    assert np.isfinite(report.final_loss)


@requires_set_mesh
def test_run_grpo_sharded_mesh():
    """One sharded GRPO step over the virtual 8-device mesh: rollout batch
    divisibility is enforced and the update executes SPMD."""
    from prime_tpu.parallel.mesh import make_mesh

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(2), config, dtype=jnp.float32)
    tok = ByteTokenizer()
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2}, devices=jax.devices()[:8])

    cfg = GrpoConfig(
        group_size=4, prompts_per_step=2, max_prompt_len=8, max_new_tokens=4,
        temperature=1.0, steps=2, kl_coef=0.05,
    )
    state, report = run_grpo(
        config, params, tok,
        examples=[{"prompt": "ab", "answer": "ab"}],
        scorer=lambda c, a: float(len(c) > 0),
        cfg=cfg,
        mesh=mesh,
        rng=jax.random.PRNGKey(3),
    )
    assert report.steps == 2
    assert np.isfinite(report.final_loss)


def test_run_grpo_batch_divisibility_error():
    from prime_tpu.parallel.mesh import make_mesh

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(2), config, dtype=jnp.float32)
    mesh = make_mesh({"dp": 4, "fsdp": 2, "tp": 1}, devices=jax.devices()[:8])
    cfg = GrpoConfig(group_size=3, prompts_per_step=1, temperature=1.0)
    with pytest.raises(ValueError, match="divisible"):
        run_grpo(
            config, params, ByteTokenizer(),
            examples=[{"prompt": "a", "answer": "a"}],
            scorer=None, cfg=cfg, mesh=mesh,
        )


# -- LoRA GRPO ---------------------------------------------------------------


def test_run_grpo_lora_trains_adapters_only():
    """GRPO with lora: the returned state holds adapter factors (base stays
    frozen), rollouts/updates go through the merged policy, and the KL
    reference is the base itself (zero KL at the zero-effect init)."""
    from prime_tpu.train.lora import LoraConfig

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(1), config, dtype=jnp.float32)
    before = jax.tree.map(jnp.copy, params)
    cfg = GrpoConfig(
        group_size=4, prompts_per_step=2, max_prompt_len=8, max_new_tokens=4,
        temperature=1.0, steps=2, kl_coef=0.05, learning_rate=1e-2,
    )
    state, report = run_grpo(
        config, params, ByteTokenizer(),
        examples=[{"prompt": "ab", "answer": "ab"}],
        scorer=lambda c, a: float(len(c) > 0),
        cfg=cfg,
        rng=jax.random.PRNGKey(5),
        lora=LoraConfig(r=4, alpha=8),
    )
    assert report.steps == 2 and np.isfinite(report.final_loss)
    # state carries {layers: {wq: {a, b}, ...}} adapter factors
    assert set(state.params["layers"]["wq"]) == {"a", "b"}
    # base weights untouched
    for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@requires_set_mesh
def test_run_grpo_lora_sharded():
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.train.lora import LoraConfig

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(2), config, dtype=jnp.float32)
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2}, devices=jax.devices()[:8])
    cfg = GrpoConfig(
        group_size=4, prompts_per_step=2, max_prompt_len=8, max_new_tokens=4,
        temperature=1.0, steps=1,
    )
    state, report = run_grpo(
        config, params, ByteTokenizer(),
        examples=[{"prompt": "xy", "answer": "xy"}],
        scorer=None, cfg=cfg, mesh=mesh, rng=jax.random.PRNGKey(6),
        lora=LoraConfig(r=4),
    )
    assert report.steps == 1 and np.isfinite(report.final_loss)


def test_run_grpo_does_not_consume_caller_params():
    """ADVICE r2: run_grpo donates its TrainState internally — the CALLER's
    params tree must stay alive and usable after the run (saving, comparing,
    a second run), not alias deleted donated buffers."""
    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(3), config, dtype=jnp.float32)
    tok = ByteTokenizer()
    cfg = GrpoConfig(
        group_size=2, prompts_per_step=1, max_prompt_len=8, max_new_tokens=4,
        temperature=1.0, steps=1,
    )
    run_grpo(
        config, params, tok,
        examples=[{"prompt": "1+1", "answer": "2"}],
        scorer=None,
        cfg=cfg,
        rng=jax.random.PRNGKey(0),
    )
    # any host-side use of the original tree must still work
    total = float(jnp.sum(params["embed"]))
    assert np.isfinite(total)


def test_run_grpo_lora_with_remat_matches_no_remat():
    """The GRPO-LoRA fused path under activation checkpointing: remat must
    change memory, not math — adapters after a rematerialized run equal the
    plain run's bit-for-bit aside from fp reassociation."""
    from prime_tpu.train.lora import LoraConfig

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(1), config, dtype=jnp.float32)

    def run(remat):
        cfg = GrpoConfig(
            group_size=4, prompts_per_step=2, max_prompt_len=8, max_new_tokens=4,
            temperature=1.0, steps=2, kl_coef=0.05, learning_rate=1e-2,
            remat=remat,
        )
        state, report = run_grpo(
            config, params, ByteTokenizer(),
            examples=[{"prompt": "ab", "answer": "ab"}],
            scorer=lambda c, a: float(len(c) > 0),
            cfg=cfg,
            rng=jax.random.PRNGKey(5),
            lora=LoraConfig(r=4, alpha=8),
        )
        assert np.isfinite(report.final_loss)
        return state

    plain = run("none")
    dots = run("dots")
    full = run("full")
    # recompute reassociates fp ops; tolerance covers that, not a math change
    for a, b in zip(jax.tree.leaves(plain.params), jax.tree.leaves(dots.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(plain.params), jax.tree.leaves(full.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
