"""Transport-level client tests with httpx mock transports.

Mirrors the reference's retry-semantics pinning approach
(prime-sandboxes/tests/test_client_retry.py:19-60): fail-then-succeed
transports, status-sequence transports, and error-mapping assertions, for both
the sync and async clients.
"""

import httpx
import pytest

from prime_tpu.core.client import APIClient, AsyncAPIClient, user_agent
from prime_tpu.core.config import Config
from prime_tpu.core.exceptions import (
    APIConnectionError,
    APIError,
    NotFoundError,
    PaymentRequiredError,
    RateLimitError,
    UnauthorizedError,
    ValidationError,
)


def make_client(handler, **kw) -> APIClient:
    cfg = Config()
    cfg.api_key = "test-key"
    return APIClient(
        config=cfg,
        base_url="https://api.test",
        transport=httpx.MockTransport(handler),
        **kw,
    )


def make_async_client(handler, **kw) -> AsyncAPIClient:
    cfg = Config()
    cfg.api_key = "test-key"
    return AsyncAPIClient(
        config=cfg,
        base_url="https://api.test",
        transport=httpx.MockTransport(handler),
        **kw,
    )


class SeqTransport(httpx.BaseTransport, httpx.AsyncBaseTransport):
    """Yields a scripted sequence of responses/exceptions, then repeats last."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def _next(self, request):
        item = self.script[min(self.calls, len(self.script) - 1)]
        self.calls += 1
        if isinstance(item, Exception):
            raise item
        status, body = item
        return httpx.Response(status, json=body, request=request)

    def handle_request(self, request):
        return self._next(request)

    async def handle_async_request(self, request):
        return self._next(request)


def seq_client(script, **kw):
    cfg = Config()
    cfg.api_key = "k"
    transport = SeqTransport(script)
    client = APIClient(config=cfg, base_url="https://api.test", transport=transport, **kw)
    return client, transport


# -- request shape -----------------------------------------------------------


def test_prefix_auth_and_user_agent():
    seen = {}

    def handler(request: httpx.Request) -> httpx.Response:
        seen["url"] = str(request.url)
        seen["auth"] = request.headers.get("Authorization")
        seen["ua"] = request.headers.get("User-Agent")
        return httpx.Response(200, json={"ok": True})

    client = make_client(handler)
    assert client.get("/pods") == {"ok": True}
    assert seen["url"] == "https://api.test/api/v1/pods"
    assert seen["auth"] == "Bearer test-key"
    assert seen["ua"] == user_agent()
    assert "prime-tpu/" in seen["ua"]


def test_team_header_injected():
    seen = {}

    def handler(request):
        seen["team"] = request.headers.get("X-Prime-Team-ID")
        return httpx.Response(200, json={})

    client = make_client(handler, team_id="team-42")
    client.get("/pods")
    assert seen["team"] == "team-42"


def test_no_double_prefix():
    def handler(request):
        assert request.url.path == "/api/v1/pods"
        return httpx.Response(200, json={})

    make_client(handler).get("/api/v1/pods")


# -- error mapping -----------------------------------------------------------


@pytest.mark.parametrize(
    "status,exc",
    [(401, UnauthorizedError), (402, PaymentRequiredError), (404, NotFoundError), (418, APIError)],
)
def test_status_error_mapping(status, exc):
    client = make_client(lambda r: httpx.Response(status, json={"detail": "boom"}))
    with pytest.raises(exc):
        client.get("/x")


def test_validation_error_field_messages():
    detail = [{"loc": ["body", "tpu_type"], "msg": "unknown TPU type", "type": "value_error"}]
    client = make_client(lambda r: httpx.Response(422, json={"detail": detail}))
    with pytest.raises(ValidationError) as ei:
        client.post("/pods")
    assert ei.value.field_messages() == ["tpu_type: unknown TPU type"]


def test_rate_limit_carries_retry_after():
    client = make_client(
        lambda r: httpx.Response(429, json={"detail": "slow down"}, headers={"Retry-After": "7"})
    )
    with pytest.raises(RateLimitError) as ei:
        client.get("/x")
    assert ei.value.retry_after == 7.0


# -- retry tiers -------------------------------------------------------------


def test_get_retries_5xx_then_succeeds(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([(502, {}), (503, {}), (200, {"ok": 1})])
    assert client.get("/x") == {"ok": 1}
    assert transport.calls == 3


def test_get_does_not_retry_non_retryable_5xx(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([(501, {}), (200, {})])
    with pytest.raises(APIError):
        client.get("/x")
    assert transport.calls == 1


def test_post_does_not_retry_5xx(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([(502, {}), (200, {})])
    with pytest.raises(APIError):
        client.post("/x")
    assert transport.calls == 1


def test_idempotent_post_retries_5xx(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([(502, {}), (200, {"ok": 1})])
    assert client.post("/x", idempotent_post=True) == {"ok": 1}
    assert transport.calls == 2


def test_post_retries_connect_error(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([httpx.ConnectError("refused"), (200, {"ok": 1})])
    assert client.post("/x") == {"ok": 1}
    assert transport.calls == 2


def test_post_does_not_retry_read_timeout(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    from prime_tpu.core.exceptions import APITimeoutError

    client, transport = seq_client([httpx.ReadTimeout("slow"), (200, {})])
    with pytest.raises(APITimeoutError):
        client.post("/x")
    assert transport.calls == 1


def test_get_retries_read_timeout(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([httpx.ReadTimeout("slow"), (200, {"ok": 1})])
    assert client.get("/x") == {"ok": 1}
    assert transport.calls == 2


def test_retries_exhaust(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    client, transport = seq_client([httpx.ConnectError("down")], max_attempts=3)
    with pytest.raises(APIConnectionError):
        client.get("/x")
    assert transport.calls == 3


# -- async mirror ------------------------------------------------------------


@pytest.mark.anyio
async def test_async_basic_and_retry(monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    cfg = Config()
    cfg.api_key = "k"
    transport = SeqTransport([(503, {}), (200, {"ok": 2})])
    client = AsyncAPIClient(config=cfg, base_url="https://api.test", transport=transport)
    assert await client.get("/pods") == {"ok": 2}
    assert transport.calls == 2
    await client.close()


@pytest.mark.anyio
async def test_async_error_mapping():
    client = make_async_client(lambda r: httpx.Response(401, json={}))
    with pytest.raises(UnauthorizedError):
        await client.get("/x")
    await client.close()


# -- review-finding regressions ----------------------------------------------


def test_idempotent_post_autogenerates_idempotency_key():
    seen = {}

    def handler(request):
        seen["key"] = request.headers.get("Idempotency-Key")
        return httpx.Response(200, json={})

    make_client(handler).post("/x", idempotent_post=True)
    assert seen["key"] and len(seen["key"]) == 36  # uuid4

    make_client(handler).post("/x", idempotent_post=True, headers={"Idempotency-Key": "mine"})
    assert seen["key"] == "mine"


def test_file_uploads_never_retried(monkeypatch, tmp_path):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    f = tmp_path / "payload.bin"
    f.write_bytes(b"x" * 100)
    client, transport = seq_client([(503, {}), (200, {})])
    with open(f, "rb") as fh, pytest.raises(APIError):
        client.put("/upload", files={"file": fh})
    assert transport.calls == 1


def test_invalid_prime_context_does_not_crash(tmp_path, monkeypatch):
    cfg = Config(tmp_path / "prime")
    cfg.api_key = "base"
    cfg.save()
    monkeypatch.setenv("PRIME_CONTEXT", "../../evil")
    assert Config(tmp_path / "prime").api_key == "base"
    # corrupt context file
    monkeypatch.setenv("PRIME_CONTEXT", "broken")
    (tmp_path / "prime" / "environments").mkdir(parents=True, exist_ok=True)
    (tmp_path / "prime" / "environments" / "broken.json").write_text("{nope")
    assert Config(tmp_path / "prime").api_key == "base"
