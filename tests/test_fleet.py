"""Serve fleet: prefix-affinity routing, failover, drain, admission control.

All fake upstreams (real InferenceServer processes over scripted backends, no
TPU): the load-bearing properties are (1) shared-prefix traffic concentrates
on one replica deterministically, (2) a replica dying mid-burst loses zero
un-streamed requests, (3) drain finishes in-flight streams while new work
reroutes, (4) saturation surfaces as 429 + Retry-After end-to-end instead of
unbounded queueing.
"""

import threading
import time
from contextlib import contextmanager

import httpx
import pytest

from prime_tpu.serve import InferenceServer
from prime_tpu.serve.errors import QueueFullError
from prime_tpu.serve.fleet import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
    FleetMembership,
    HashRing,
    PrefixAffinityBalancer,
    affinity_key,
    serve_fleet,
)
from prime_tpu.serve.fleet import balancer as balancer_mod

# long enough for a text affinity key (>= MIN_BUCKET * CHARS_PER_TOKEN chars)
PREAMBLE = "You are a terse and helpful assistant for the fleet routing test. " * 3


class FleetBackend:
    """Scripted replica backend: replies with its own name so tests can see
    exactly where the router sent each request."""

    concurrent = True

    def __init__(self, name: str, delay: float = 0.0):
        self.name = name
        self.delay = delay
        self.calls: list[str] = []
        self.queue_depth = 0
        self.active_slots = 0
        self.max_slots = 8
        self.submit_error: Exception | None = None

    def stats(self):
        return {
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "max_slots": self.max_slots,
        }

    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        if self.submit_error is not None:
            raise self.submit_error
        self.calls.append(prompts[0])
        if self.delay:
            time.sleep(self.delay)
        return [self.name] * len(prompts)


@contextmanager
def make_fleet(backends, **router_kw):
    router_kw.setdefault("poll_interval", 0.05)
    router_kw.setdefault("model_id", "tiny-test")
    servers = [InferenceServer("tiny-test", b, port=0).start() for b in backends]
    router = serve_fleet([srv.url for srv in servers], **router_kw)
    try:
        yield router, servers
    finally:
        router.stop()
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — a test may have stopped one already
                pass


def chat(url: str, content: str, timeout: float = 30.0) -> httpx.Response:
    return httpx.post(
        f"{url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": content}]},
        timeout=timeout,
    )


# ---- balancer units ---------------------------------------------------------


def test_affinity_block_matches_engine_min_bucket():
    """The routing key's block size must equal the engine prefix cache's
    MIN_BUCKET — same alignment, or prompts that share cached KV blocks
    would not share a routing key."""
    from prime_tpu.serve.engine import MIN_BUCKET

    assert balancer_mod.MIN_BUCKET == MIN_BUCKET


def test_affinity_key_alignment_and_sharing():
    # token ids: block-aligned, capped at `blocks` blocks
    assert affinity_key(list(range(15))) is None  # under one block
    a = affinity_key(list(range(64)))
    b = affinity_key(list(range(32)) + [99] * 32)
    assert a == b  # same leading 2 blocks -> same key
    assert affinity_key(list(range(32))) == a  # exactly the cap
    # text: shares the leading blocks -> shares the key; short text -> None
    assert affinity_key("x") is None
    assert affinity_key(PREAMBLE + "tail one") == affinity_key(PREAMBLE + "other tail")
    # deterministic across calls (sha1, not PYTHONHASHSEED-dependent)
    assert affinity_key(PREAMBLE + "q") == affinity_key(PREAMBLE + "q")


def test_hash_ring_minimal_remap():
    """Consistent hashing: removing one member only remaps the keys that
    member owned — everyone else's affinity target survives the change."""
    ring = HashRing(vnodes=64)
    ring.build(["a:1", "b:2", "c:3"])
    keys = [("ids", (i,) * 32) for i in range(200)]
    owners = {k: ring.candidates(k)[0] for k in keys}
    ring2 = HashRing(vnodes=64)
    ring2.build(["a:1", "c:3"])
    for key, owner in owners.items():
        if owner != "b:2":
            assert ring2.candidates(key)[0] == owner


def test_balancer_least_loaded_fallback_on_saturation():
    m = FleetMembership(["http://127.0.0.1:1", "http://127.0.0.1:2"])
    b = PrefixAffinityBalancer(m)
    target = b.pick(PREAMBLE).replica
    other = next(r for r in m.replicas.values() if r.id != target.id)
    # saturate the affinity target: queued work means new requests wait
    target.queue_depth = 3
    pick = b.pick(PREAMBLE)
    assert pick.replica.id == other.id
    assert pick.rerouted and pick.affinity and not pick.hit
    # unsaturated again: back to the hash target (cache affinity restored)
    target.queue_depth = 0
    assert b.pick(PREAMBLE).replica.id == target.id


def test_balancer_excludes_failed_replica():
    m = FleetMembership(["http://127.0.0.1:1", "http://127.0.0.1:2"])
    b = PrefixAffinityBalancer(m)
    first = b.pick(PREAMBLE).replica
    retry = b.pick(PREAMBLE, exclude={first.id})
    assert retry is not None and retry.replica.id != first.id
    assert b.pick(PREAMBLE, exclude={r.id for r in m.replicas.values()}) is None


def test_router_side_drain_is_sticky_across_polls():
    """A drained replica must stay out of rotation even when the remote
    /admin/drain POST never landed and its /healthz keeps answering ready."""
    backend = FleetBackend("replica-a")
    server = InferenceServer("tiny-test", backend, port=0).start()
    try:
        m = FleetMembership([server.url])
        rid = next(iter(m.replicas))
        m.drain(rid, remote=False)  # the replica itself was never told
        m.poll_once(m.replicas[rid])  # upstream still reports ready
        assert m.replicas[rid].state == "draining"
        assert rid not in {r.id for r in m.routable_replicas()}
    finally:
        server.stop()


def test_breaker_opens_after_threshold_and_half_opens_after_cooldown():
    m = FleetMembership(
        ["http://127.0.0.1:9", "http://127.0.0.1:10"],
        fail_threshold=3, cooldown=0.1,
    )
    dead = next(iter(m.replicas.values()))
    for _ in range(2):
        m.note_failure(dead.id)
    assert dead.breaker == BREAKER_CLOSED  # under threshold
    m.note_failure(dead.id)
    assert dead.breaker == BREAKER_OPEN
    assert dead.id not in {r.id for r in m.routable_replicas()}
    time.sleep(0.15)
    # cooldown lapsed: half-open, routable as a trial
    assert dead.id in {r.id for r in m.routable_replicas()}
    # trial failure re-opens immediately (no need for a full new streak)
    m.note_failure(dead.id)
    assert dead.breaker == BREAKER_OPEN
    time.sleep(0.15)
    m.routable_replicas()  # half-open again
    m.note_success(dead.id)
    assert dead.breaker == BREAKER_CLOSED and dead.consecutive_failures == 0


# ---- routing over live fake replicas ---------------------------------------


def test_affinity_routing_concentrates_shared_prefix():
    """The acceptance bar: a shared-prefix burst routes >= 90% of requests to
    ONE replica, and the router's metrics expose the hit ratio."""
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, _servers):
        replies = [
            chat(router.url, f"{PREAMBLE} question {i}").json()["choices"][0]["message"]["content"]
            for i in range(20)
        ]
        top = max(replies.count("replica-a"), replies.count("replica-b"))
        assert top >= 18  # >= 90% on one replica (sha1 target: actually all 20)
        stats = router.stats()
        assert stats["affinity_requests"] == 20
        assert stats["affinity_hit_ratio"] >= 0.9
        # the ratio is also a scrape-able gauge
        text = httpx.get(f"{router.url}/metrics", params={"format": "prometheus"}).text
        assert "fleet_affinity_hit_ratio" in text


def test_distinct_prefixes_spread_across_replicas():
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, _servers):
        for i in range(16):
            prefix = f"System prompt variant {i}: " + f"filler-{i} " * 12
            assert chat(router.url, prefix + "q").status_code == 200
        assert a.calls and b.calls  # consistent hashing spread the keys


def test_failover_mid_burst_loses_no_requests():
    """Kill the replica carrying the affinity traffic mid-burst: every
    un-streamed request must reroute to the survivor and succeed."""
    a, b = FleetBackend("replica-a", delay=0.02), FleetBackend("replica-b", delay=0.02)
    with make_fleet([a, b], fail_threshold=2, cooldown=5.0) as (router, servers):
        # find the affinity target with one probe request
        probe = chat(router.url, f"{PREAMBLE} probe").json()
        victim_name = probe["choices"][0]["message"]["content"]
        victim_srv = servers[0] if victim_name == "replica-a" else servers[1]

        results: list[str] = []
        errors: list[Exception] = []
        lock = threading.Lock()

        def worker(i: int) -> None:
            try:
                response = chat(router.url, f"{PREAMBLE} burst {i}", timeout=30)
                assert response.status_code == 200, response.text
                name = response.json()["choices"][0]["message"]["content"]
                with lock:
                    results.append(name)
            except Exception as e:  # noqa: BLE001 — collected for the assert
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(24)]
        for i, t in enumerate(threads):
            t.start()
            if i == 7:
                victim_srv.stop()  # mid-burst: later connects get refused
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 24  # zero lost requests
        survivor = "replica-b" if victim_name == "replica-a" else "replica-a"
        assert survivor in results  # the survivor picked up rerouted work
        stats = router.stats()
        # the dead replica was detected either by a live request taking the
        # connect-error reroute path or by the 0.05s health poller tripping
        # the breaker first — which one wins is a race, both are correct, and
        # either way the breaker has accumulated the failure streak by now
        assert stats["replicas"][_rid(victim_srv)]["breaker"] == BREAKER_OPEN


def _rid(server: InferenceServer) -> str:
    from prime_tpu.serve.fleet.membership import replica_id_for

    return replica_id_for(server.url)


# ---- drain ------------------------------------------------------------------


class StreamingBackend(FleetBackend):
    """Backend with true live streaming: deltas trickle out so a drain can
    land mid-stream."""

    def __init__(self, name: str, n_deltas: int = 6, delta_s: float = 0.05):
        super().__init__(name)
        self.n_deltas = n_deltas
        self.delta_s = delta_s
        self.first_delta = threading.Event()

    def submit_text(self, prompt, max_new_tokens, temperature, top_p=1.0, templated=False):
        if self.submit_error is not None:
            raise self.submit_error
        self.calls.append(prompt)
        return object()

    def stream_text(self, req, timeout=None):
        for i in range(self.n_deltas):
            self.first_delta.set()
            time.sleep(self.delta_s)
            yield f"{self.name}:{i} "


def test_drain_completes_inflight_stream_and_reroutes_new_work():
    a = StreamingBackend("replica-a")
    b = StreamingBackend("replica-b")
    with make_fleet([a, b]) as (router, servers):
        probe = chat(router.url, f"{PREAMBLE} probe").json()
        victim_name = probe["choices"][0]["message"]["content"].split(":")[0]
        victim_idx = 0 if victim_name == "replica-a" else 1
        victim_srv = servers[victim_idx]
        victim_backend = (a, b)[victim_idx]
        victim_backend.first_delta.clear()

        deltas: list[str] = []
        done = threading.Event()

        def consume() -> None:
            with httpx.stream(
                "POST",
                f"{router.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": f"{PREAMBLE} stream"}],
                      "stream": True},
                timeout=30,
            ) as response:
                assert response.status_code == 200
                for line in response.iter_lines():
                    if line.startswith("data:") and "[DONE]" not in line:
                        deltas.append(line)
            done.set()

        t = threading.Thread(target=consume)
        t.start()
        assert victim_backend.first_delta.wait(timeout=10)  # stream is live
        # drain the replica mid-stream through the router's admin surface
        response = httpx.post(
            f"{router.url}/admin/drain", params={"replica": _rid(victim_srv)}, timeout=5
        )
        assert response.status_code == 200
        # the in-flight stream must run to completion (drain != kill)
        assert done.wait(timeout=30)
        t.join(timeout=5)
        payloads = [d for d in deltas if victim_name in d]
        assert len(payloads) >= victim_backend.n_deltas  # every delta arrived
        # the drained replica reports 503/draining on its own healthz...
        health = httpx.get(f"{victim_srv.url}/healthz", timeout=5)
        assert health.status_code == 503
        assert health.json()["state"] == "draining"
        # ...refuses new work directly...
        assert chat(victim_srv.url, "direct").status_code == 503
        # ...and the router sends every new request to the survivor
        survivor = "replica-b" if victim_name == "replica-a" else "replica-a"
        for i in range(4):
            body = chat(router.url, f"{PREAMBLE} after-drain {i}").json()
            assert body["choices"][0]["message"]["content"].startswith(survivor)


# ---- admission control / 429 ------------------------------------------------


def test_router_admission_gate_429_with_retry_after():
    slow = FleetBackend("replica-a", delay=0.6)
    with make_fleet([slow], max_inflight=1, queue_wait_s=0.05) as (router, _servers):
        codes: list[int] = []
        headers: list[str | None] = []
        lock = threading.Lock()

        def worker() -> None:
            response = chat(router.url, f"{PREAMBLE} x", timeout=30)
            with lock:
                codes.append(response.status_code)
                headers.append(response.headers.get("Retry-After"))

        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
            time.sleep(0.1)  # guarantee overlap with the 0.6 s in-flight call
        for t in threads:
            t.join(timeout=30)
        assert codes.count(200) >= 1
        assert codes.count(429) >= 1
        rejected = [h for c, h in zip(codes, headers) if c == 429]
        assert all(h is not None and float(h) > 0 for h in rejected)
        assert router.stats()["admission_rejected"] >= 1


def test_upstream_429_fails_over_then_propagates():
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    a.submit_error = QueueFullError("full", retry_after=0.2)
    with make_fleet([a, b]) as (router, _servers):
        # one replica shedding load: the request lands on the other
        response = chat(router.url, f"{PREAMBLE} one-full")
        assert response.status_code == 200
        assert response.json()["choices"][0]["message"]["content"] == "replica-b"
        # the whole fleet shedding load: 429 + Retry-After reaches the client
        b.submit_error = QueueFullError("full", retry_after=0.2)
        response = chat(router.url, f"{PREAMBLE} all-full")
        assert response.status_code == 429
        # integer delta-seconds passthrough from the last replica's 429
        assert response.headers["Retry-After"] == "1"
        assert response.json()["error"]["retry_after"] == pytest.approx(0.2)
        assert router.stats()["reroutes"].get("upstream_429", 0) >= 1


def test_client_survives_router_backpressure(monkeypatch, tmp_path):
    """End-to-end satellite: engine-style 429s propagate through the router
    and the SDK's InferenceClient rides them out via Retry-After."""
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    monkeypatch.setenv("PRIME_API_KEY", "local")

    from prime_tpu.api.inference import InferenceClient
    from prime_tpu.core.config import Config

    flaky = FleetBackend("replica-a")
    attempts = {"n": 0}

    real_generate = flaky.generate

    def generate(prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        attempts["n"] += 1
        if attempts["n"] <= 2:
            raise QueueFullError("warming up", retry_after=0.05)
        return real_generate(prompts, max_new_tokens, temperature, top_p, templated)

    flaky.generate = generate
    with make_fleet([flaky]) as (router, _servers):
        client = InferenceClient(
            config=Config(), base_url=f"{router.url}/v1", max_429_retries=3
        )
        reply = client.chat_completion(
            "tiny-test", [{"role": "user", "content": f"{PREAMBLE} retry me"}]
        )
        assert reply["choices"][0]["message"]["content"] == "replica-a"
        assert attempts["n"] == 3  # two 429s ridden out, third attempt served


def test_client_gives_up_after_bounded_retries(monkeypatch, tmp_path):
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    monkeypatch.setenv("PRIME_API_KEY", "local")

    from prime_tpu.api.inference import InferenceClient
    from prime_tpu.core.config import Config
    from prime_tpu.core.exceptions import RateLimitError

    full = FleetBackend("replica-a")
    full.submit_error = QueueFullError("permanently full", retry_after=0.02)
    with make_fleet([full]) as (router, _servers):
        client = InferenceClient(
            config=Config(), base_url=f"{router.url}/v1", max_429_retries=1
        )
        with pytest.raises(RateLimitError) as excinfo:
            client.chat_completion("tiny-test", [{"role": "user", "content": "x"}])
        assert excinfo.value.retry_after is not None


# ---- router surface ---------------------------------------------------------


def test_router_healthz_metrics_and_admin_surfaces():
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, servers):
        health = httpx.get(f"{router.url}/healthz", timeout=5)
        assert health.status_code == 200
        assert health.json()["routable"] == 2
        fleet = httpx.get(f"{router.url}/admin/fleet", timeout=5).json()
        assert set(fleet["replicas"]) == {_rid(servers[0]), _rid(servers[1])}
        models = httpx.get(f"{router.url}/v1/models", timeout=5).json()
        assert models["data"][0]["id"] == "tiny-test"
        registry = httpx.get(
            f"{router.url}/metrics", params={"format": "registry"}, timeout=5
        ).json()
        assert "fleet_requests_total" in registry["router"]
        assert httpx.get(f"{router.url}/nope", timeout=5).status_code == 404


def test_router_join_registers_new_replica():
    a = FleetBackend("replica-a")
    with make_fleet([a]) as (router, _servers):
        late = InferenceServer("tiny-test", FleetBackend("replica-late"), port=0).start()
        try:
            response = httpx.post(
                f"{router.url}/admin/join", json={"url": late.url}, timeout=5
            )
            assert response.status_code == 200
            assert response.json()["joined"] == _rid(late)
            assert _rid(late) in router.stats()["replicas"]
        finally:
            late.stop()


def test_router_forwards_attribution_headers():
    """X-PI-Job-Id / Authorization etc. must survive the proxy hop — a
    production upstream authorizes and attributes on them."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    seen: dict[str, str] = {}

    class Upstream(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, payload: dict) -> None:
            body = _json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._send({"state": "ready", "queue_depth": 0, "active_slots": 0})

        def do_POST(self):
            seen.update({k: v for k, v in self.headers.items()})
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self._send({"choices": [{"message": {"content": "ok"}}]})

    upstream = ThreadingHTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=upstream.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{upstream.server_address[1]}"
    router = serve_fleet([url], poll_interval=0.05, model_id="tiny-test")
    try:
        response = httpx.post(
            f"{router.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
            headers={"X-PI-Job-Id": "job-7", "Authorization": "Bearer tok"},
            timeout=10,
        )
        assert response.status_code == 200
        assert seen.get("X-PI-Job-Id") == "job-7"
        assert seen.get("Authorization") == "Bearer tok"
        # hop-by-hop/host were rewritten for the upstream connection
        assert seen.get("Host", "").endswith(str(upstream.server_address[1]))
    finally:
        router.stop()
        upstream.shutdown()
        upstream.server_close()


def test_admin_surface_token_gate_and_join_validation():
    a = FleetBackend("replica-a")
    with make_fleet([a], admin_token="sekrit") as (router, servers):
        assert chat(router.url, "x").status_code == 200  # data plane open
        rid = _rid(servers[0])
        denied = httpx.post(
            f"{router.url}/admin/drain", params={"replica": rid}, timeout=5
        )
        assert denied.status_code == 403
        auth = {"Authorization": "Bearer sekrit"}
        # malformed join payloads answer 400, not a dropped connection
        bad = httpx.post(
            f"{router.url}/admin/join", json={"url": 123}, headers=auth, timeout=5
        )
        assert bad.status_code == 400
        ok = httpx.post(
            f"{router.url}/admin/drain", params={"replica": rid}, headers=auth, timeout=5
        )
        assert ok.status_code == 200


def test_router_healthz_unavailable_when_all_replicas_down():
    a = FleetBackend("replica-a")
    with make_fleet([a], fail_threshold=1, cooldown=30.0) as (router, servers):
        servers[0].stop()
        # one failed request trips the breaker (threshold 1)
        assert chat(router.url, "x").status_code == 503
        health = httpx.get(f"{router.url}/healthz", timeout=5)
        assert health.status_code == 503
        assert health.json()["state"] == "unavailable"
