"""Serve fleet: prefix-affinity routing, failover, drain, admission control.

All fake upstreams (real InferenceServer processes over scripted backends, no
TPU): the load-bearing properties are (1) shared-prefix traffic concentrates
on one replica deterministically, (2) a replica dying mid-burst loses zero
un-streamed requests, (3) drain finishes in-flight streams while new work
reroutes, (4) saturation surfaces as 429 + Retry-After end-to-end instead of
unbounded queueing.
"""

import threading
import time
from contextlib import contextmanager
from pathlib import Path

import httpx
import pytest

from prime_tpu.serve import InferenceServer
from prime_tpu.serve.errors import QueueFullError
from prime_tpu.serve.fleet import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
    FleetMembership,
    HashRing,
    PrefixAffinityBalancer,
    affinity_key,
    serve_fleet,
)
from prime_tpu.serve.fleet import balancer as balancer_mod

# long enough for a text affinity key (>= MIN_BUCKET * CHARS_PER_TOKEN chars)
PREAMBLE = "You are a terse and helpful assistant for the fleet routing test. " * 3


class FleetBackend:
    """Scripted replica backend: replies with its own name so tests can see
    exactly where the router sent each request."""

    concurrent = True
    # the scripted replica plays a cache-capable engine: without this the
    # server suppresses /healthz prefix_digest (a cacheless replica must
    # not attract cache-aware reroutes)
    prefix_cache_enabled = True

    def __init__(self, name: str, delay: float = 0.0):
        self.name = name
        self.delay = delay
        self.calls: list[str] = []
        self.queue_depth = 0
        self.active_slots = 0
        self.max_slots = 8
        self.submit_error: Exception | None = None

    def stats(self):
        return {
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "max_slots": self.max_slots,
        }

    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        if self.submit_error is not None:
            raise self.submit_error
        self.calls.append(prompts[0])
        if self.delay:
            time.sleep(self.delay)
        return [self.name] * len(prompts)


@contextmanager
def make_fleet(backends, **router_kw):
    router_kw.setdefault("poll_interval", 0.05)
    router_kw.setdefault("model_id", "tiny-test")
    servers = [InferenceServer("tiny-test", b, port=0).start() for b in backends]
    router = serve_fleet([srv.url for srv in servers], **router_kw)
    try:
        yield router, servers
    finally:
        router.stop()
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — a test may have stopped one already
                pass


def chat(url: str, content: str, timeout: float = 30.0) -> httpx.Response:
    return httpx.post(
        f"{url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": content}]},
        timeout=timeout,
    )


# ---- balancer units ---------------------------------------------------------


def test_affinity_block_matches_engine_min_bucket():
    """The routing key's block size must equal the engine prefix cache's
    MIN_BUCKET — same alignment, or prompts that share cached KV blocks
    would not share a routing key."""
    from prime_tpu.serve.engine import MIN_BUCKET

    assert balancer_mod.MIN_BUCKET == MIN_BUCKET


def test_affinity_key_alignment_and_sharing():
    # token ids: block-aligned, capped at `blocks` blocks
    assert affinity_key(list(range(15))) is None  # under one block
    a = affinity_key(list(range(64)))
    b = affinity_key(list(range(32)) + [99] * 32)
    assert a == b  # same leading 2 blocks -> same key
    assert affinity_key(list(range(32))) == a  # exactly the cap
    # text: shares the leading blocks -> shares the key; short text -> None
    assert affinity_key("x") is None
    assert affinity_key(PREAMBLE + "tail one") == affinity_key(PREAMBLE + "other tail")
    # deterministic across calls (sha1, not PYTHONHASHSEED-dependent)
    assert affinity_key(PREAMBLE + "q") == affinity_key(PREAMBLE + "q")


def test_hash_ring_minimal_remap():
    """Consistent hashing: removing one member only remaps the keys that
    member owned — everyone else's affinity target survives the change."""
    ring = HashRing(vnodes=64)
    ring.build(["a:1", "b:2", "c:3"])
    keys = [("ids", (i,) * 32) for i in range(200)]
    owners = {k: ring.candidates(k)[0] for k in keys}
    ring2 = HashRing(vnodes=64)
    ring2.build(["a:1", "c:3"])
    for key, owner in owners.items():
        if owner != "b:2":
            assert ring2.candidates(key)[0] == owner


def test_balancer_least_loaded_fallback_on_saturation():
    m = FleetMembership(["http://127.0.0.1:1", "http://127.0.0.1:2"])
    b = PrefixAffinityBalancer(m)
    target = b.pick(PREAMBLE).replica
    other = next(r for r in m.replicas.values() if r.id != target.id)
    # saturate the affinity target: queued work means new requests wait
    target.queue_depth = 3
    pick = b.pick(PREAMBLE)
    assert pick.replica.id == other.id
    assert pick.rerouted and pick.affinity and not pick.hit
    # unsaturated again: back to the hash target (cache affinity restored)
    target.queue_depth = 0
    assert b.pick(PREAMBLE).replica.id == target.id


def test_balancer_excludes_failed_replica():
    m = FleetMembership(["http://127.0.0.1:1", "http://127.0.0.1:2"])
    b = PrefixAffinityBalancer(m)
    first = b.pick(PREAMBLE).replica
    retry = b.pick(PREAMBLE, exclude={first.id})
    assert retry is not None and retry.replica.id != first.id
    assert b.pick(PREAMBLE, exclude={r.id for r in m.replicas.values()}) is None


def test_digest_block_pins_engine_and_balancer_alignment():
    """The digest's block size and text proxy must equal the balancer's and
    the engine's MIN_BUCKET: replica advertisement, router probe, and radix
    tree all hash the same block boundaries or no chain ever matches."""
    from prime_tpu.serve import digest
    from prime_tpu.serve.engine import MIN_BUCKET

    assert digest.MIN_BUCKET == balancer_mod.MIN_BUCKET == MIN_BUCKET
    assert digest.CHARS_PER_TOKEN == balancer_mod.CHARS_PER_TOKEN


def test_digest_hash_chain_prefix_stability():
    from prime_tpu.serve.digest import longest_match_blocks, prefix_hashes

    # suffixes long enough that both chains reach a block PAST the shared
    # preamble — that block must diverge
    a = prefix_hashes(PREAMBLE + "tail one " * 12)
    b = prefix_hashes(PREAMBLE + "another ending " * 8)
    shared = len(PREAMBLE) // 64  # full shared 64-char blocks
    assert shared >= 2 and len(a) > shared and len(b) > shared
    assert a[:shared] == b[:shared]
    # deterministic; divergent suffix diverges the chain from there on
    assert a == prefix_hashes(PREAMBLE + "tail one " * 12)
    assert a[shared] != b[shared]
    # ids and text hash into disjoint spaces: equal lengths never collide
    ids = prefix_hashes(list(range(64)))
    assert not set(ids) & set(prefix_hashes("x" * 64 * 4))
    # under one block -> no chain
    assert prefix_hashes("short") == [] and prefix_hashes([1, 2, 3]) == []
    # the DEEPEST advertised entry wins, tolerating aged-out mid-chain gaps
    assert longest_match_blocks(a, frozenset({a[0], a[2]})) == 3
    assert longest_match_blocks(a, frozenset()) == 0


def test_digest_lru_bound_and_snapshot_merge():
    from prime_tpu.serve.digest import HotPrefixDigest, prefix_hashes

    d = HotPrefixDigest(max_entries=4)
    d.observe(PREAMBLE + "one")       # chain of >= 3 entries
    d.observe("y" * 256)              # 4 more: the oldest age out
    assert len(d) == 4
    snap = d.snapshot(extra=[123, 456])
    assert snap["version"] == 1 and snap["block"] == 16
    # own text entries lead (the only space today's router can probe) and
    # the id-space extras are truncated off a full advertisement
    assert snap["hashes"] == d.hashes()[:4]
    roomy = HotPrefixDigest(max_entries=8)
    roomy.observe("y" * 256)  # 4 text entries: extras fit in the remainder
    assert roomy.snapshot(extra=[123, 456])["hashes"][-2:] == [123, 456]
    # a short prompt contributes nothing
    d2 = HotPrefixDigest()
    d2.observe("hi")
    assert len(d2) == 0
    assert prefix_hashes(PREAMBLE)[0] in HotPrefixDigest().snapshot(
        extra=prefix_hashes(PREAMBLE)
    )["hashes"]


def test_membership_tolerates_pre_digest_and_malformed_healthz():
    """Satellite: /healthz payloads from older replicas (no prefix_digest
    field) or buggy ones (junk shapes, junk entries, oversized lists) must
    parse to an empty/capped digest — never a KeyError, never a poll
    failure."""
    from prime_tpu.serve.digest import RETAIN_MAX_ENTRIES

    m = FleetMembership(["http://127.0.0.1:1"])
    replica = next(iter(m.replicas.values()))
    # pre-digest schema: field absent entirely
    m.apply_health(replica, {"state": "ready", "queue_depth": 2}, 200)
    assert replica.digest == frozenset() and replica.state == "ready"
    assert replica.queue_depth == 2
    # junk shapes and junk entries degrade, never raise
    for junk in ("nope", 7, ["h"], {"hashes": "nope"}, {"hashes": {"a": 1}}):
        m.apply_health(replica, {"state": "ready", "prefix_digest": junk}, 200)
        assert replica.digest == frozenset()
    # junk load VALUES coerce to 0 instead of raising mid-update
    m.apply_health(
        replica,
        {"state": "ready", "queue_depth": "busy", "active_slots": [1], "max_slots": None},
        200,
    )
    assert (replica.queue_depth, replica.active_slots, replica.max_slots) == (0, 0, 0)
    m.apply_health(
        replica,
        {"prefix_digest": {"hashes": [1, True, "x", 2.5, None, 2]}},
        200,
    )
    assert replica.digest == frozenset({1, 2})
    # oversized advertisement: retention capped per replica
    m.apply_health(
        replica,
        {"prefix_digest": {"hashes": list(range(RETAIN_MAX_ENTRIES + 500))}},
        200,
    )
    assert len(replica.digest) == RETAIN_MAX_ENTRIES
    assert "digest_entries" in m.snapshot()[replica.id]


def test_membership_role_schema_tolerance():
    """Satellite: the /healthz ``role`` field parses with the same tolerance
    contract as the prefix digest — unknown/absent/junk coerces to ``any``
    (never a poll failure), and the closed role vocabulary is the memory cap
    (a replica cannot balloon router state through it the way an unbounded
    digest could)."""
    m = FleetMembership(["http://127.0.0.1:1"])
    replica = next(iter(m.replicas.values()))
    # pre-role schema: field absent entirely -> the every-phase role
    m.apply_health(replica, {"state": "ready"}, 200)
    assert replica.role == "any"
    # explicit roles land
    for role in ("prefill", "decode", "any"):
        m.apply_health(replica, {"state": "ready", "role": role}, 200)
        assert replica.role == role
    # junk values/shapes degrade to "any", never raise — and never leave a
    # stale explicit role behind (a replica that STOPS advertising must not
    # keep attracting migrations)
    for junk in (7, True, None, "", "PREFILL", "gpu", ["decode"], {"r": 1}, "x" * 4096):
        m.apply_health(replica, {"state": "ready", "role": junk}, 200)
        assert replica.role == "any", junk
    assert m.snapshot()[replica.id]["role"] == "any"


def test_balancer_cache_aware_fallback_routes_to_longest_prefix():
    """The tentpole routing upgrade: with the affinity target saturated, the
    fallback diverts to the unsaturated replica advertising the LONGEST
    cached prefix of this request — deterministically — and only falls back
    to blind least-loaded when nobody advertises a match."""
    from prime_tpu.serve.digest import prefix_hashes

    urls = [f"http://127.0.0.1:{p}" for p in (1, 2, 3)]
    m = FleetMembership(urls)
    b = PrefixAffinityBalancer(m)
    prompt = PREAMBLE + "the question"
    target = b.pick(prompt).replica
    others = [r for r in m.replicas.values() if r.id != target.id]
    chain = prefix_hashes(prompt)
    assert len(chain) >= 3
    target.queue_depth = 5  # saturate the affinity target
    # nobody advertises: blind least-loaded (not cache-routed)
    pick = b.pick(prompt)
    assert pick.rerouted and not pick.cache_routed
    # shallow vs deep advertisement: the deeper one wins even when the
    # shallow one is less loaded
    others[0].digest = frozenset(chain[:1])
    others[1].digest = frozenset(chain[:3])
    others[1].active_slots = 3
    for _ in range(3):  # deterministic across repeated picks
        pick = b.pick(prompt)
        assert pick.replica.id == others[1].id
        assert pick.cache_routed and pick.rerouted and not pick.hit
        assert pick.cached_blocks == 3
    # a saturated advertiser is no candidate: divert to the shallow one
    others[1].queue_depth = 9
    pick = b.pick(prompt)
    assert pick.replica.id == others[0].id and pick.cached_blocks == 1
    # digests that match nothing -> blind least-loaded fallback
    others[0].digest = frozenset({10, 11})
    others[1].digest = frozenset({12})
    pick = b.pick(prompt)
    assert pick.rerouted and not pick.cache_routed
    # target unsaturated again: affinity hit resumes, no probing
    target.queue_depth = 0
    pick = b.pick(prompt)
    assert pick.hit and not pick.rerouted and not pick.cache_routed


def test_router_side_drain_is_sticky_across_polls():
    """A drained replica must stay out of rotation even when the remote
    /admin/drain POST never landed and its /healthz keeps answering ready."""
    backend = FleetBackend("replica-a")
    server = InferenceServer("tiny-test", backend, port=0).start()
    try:
        m = FleetMembership([server.url])
        rid = next(iter(m.replicas))
        m.drain(rid, remote=False)  # the replica itself was never told
        m.poll_once(m.replicas[rid])  # upstream still reports ready
        assert m.replicas[rid].state == "draining"
        assert rid not in {r.id for r in m.routable_replicas()}
    finally:
        server.stop()


def test_breaker_opens_after_threshold_and_half_opens_after_cooldown():
    m = FleetMembership(
        ["http://127.0.0.1:9", "http://127.0.0.1:10"],
        fail_threshold=3, cooldown=0.1,
    )
    dead = next(iter(m.replicas.values()))
    for _ in range(2):
        m.note_failure(dead.id)
    assert dead.breaker == BREAKER_CLOSED  # under threshold
    m.note_failure(dead.id)
    assert dead.breaker == BREAKER_OPEN
    assert dead.id not in {r.id for r in m.routable_replicas()}
    time.sleep(0.15)
    # cooldown lapsed: half-open, routable as a trial
    assert dead.id in {r.id for r in m.routable_replicas()}
    # trial failure re-opens immediately (no need for a full new streak)
    m.note_failure(dead.id)
    assert dead.breaker == BREAKER_OPEN
    time.sleep(0.15)
    m.routable_replicas()  # half-open again
    m.note_success(dead.id)
    assert dead.breaker == BREAKER_CLOSED and dead.consecutive_failures == 0


# ---- routing over live fake replicas ---------------------------------------


def test_affinity_routing_concentrates_shared_prefix():
    """The acceptance bar: a shared-prefix burst routes >= 90% of requests to
    ONE replica, and the router's metrics expose the hit ratio."""
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, _servers):
        replies = [
            chat(router.url, f"{PREAMBLE} question {i}").json()["choices"][0]["message"]["content"]
            for i in range(20)
        ]
        top = max(replies.count("replica-a"), replies.count("replica-b"))
        assert top >= 18  # >= 90% on one replica (sha1 target: actually all 20)
        stats = router.stats()
        assert stats["affinity_requests"] == 20
        assert stats["affinity_hit_ratio"] >= 0.9
        # the ratio is also a scrape-able gauge
        text = httpx.get(f"{router.url}/metrics", params={"format": "prometheus"}).text
        assert "fleet_affinity_hit_ratio" in text


def test_distinct_prefixes_spread_across_replicas():
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, _servers):
        for i in range(16):
            prefix = f"System prompt variant {i}: " + f"filler-{i} " * 12
            assert chat(router.url, prefix + "q").status_code == 200
        assert a.calls and b.calls  # consistent hashing spread the keys


def test_cache_aware_reroute_e2e_over_healthz_digests():
    """Tentpole e2e: both replicas have served (and therefore advertise) a
    shared prefix; when the affinity target saturates, the router's next
    request diverts to the OTHER replica because its polled /healthz digest
    covers the prefix — visible as reroutes{reason="cache"} and
    fleet_cache_routed_total, not a blind least-loaded divert."""
    from prime_tpu.serve.server import render_chat_prompt

    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, servers):
        content = PREAMBLE + "what is the plan?"
        # warm BOTH replicas directly (not via the router): each serves the
        # prefix once and starts advertising its hash chain on /healthz
        for srv in servers:
            assert chat(srv.url, content).status_code == 200
        router.membership.poll_all()
        with router.membership._lock:
            assert all(
                r.digest for r in router.membership.replicas.values()
            ), "healthz advertisement never reached the router"
        # find and saturate the affinity target's backend
        rendered = render_chat_prompt([{"role": "user", "content": content}])
        target = router.balancer.pick(rendered).replica
        target_backend = next(
            be for be, srv in zip([a, b], servers) if srv.url == target.url
        )
        other_backend = a if target_backend is b else b
        target_backend.queue_depth = 5
        router.membership.poll_all()
        reply = chat(router.url, content).json()["choices"][0]["message"]["content"]
        assert reply == other_backend.name
        stats = router.stats()
        assert stats["cache_routed"] == 1
        assert stats["reroutes"].get("cache") == 1
        text = httpx.get(
            f"{router.url}/metrics", params={"format": "prometheus"}
        ).text
        assert "fleet_cache_routed_total 1" in text


def test_failover_mid_burst_loses_no_requests():
    """Kill the replica carrying the affinity traffic mid-burst: every
    un-streamed request must reroute to the survivor and succeed."""
    a, b = FleetBackend("replica-a", delay=0.02), FleetBackend("replica-b", delay=0.02)
    with make_fleet([a, b], fail_threshold=2, cooldown=5.0) as (router, servers):
        # find the affinity target with one probe request
        probe = chat(router.url, f"{PREAMBLE} probe").json()
        victim_name = probe["choices"][0]["message"]["content"]
        victim_srv = servers[0] if victim_name == "replica-a" else servers[1]

        results: list[str] = []
        errors: list[Exception] = []
        lock = threading.Lock()

        def worker(i: int) -> None:
            try:
                response = chat(router.url, f"{PREAMBLE} burst {i}", timeout=30)
                assert response.status_code == 200, response.text
                name = response.json()["choices"][0]["message"]["content"]
                with lock:
                    results.append(name)
            except Exception as e:  # noqa: BLE001 — collected for the assert
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(24)]
        for i, t in enumerate(threads):
            t.start()
            if i == 7:
                victim_srv.stop()  # mid-burst: later connects get refused
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 24  # zero lost requests
        survivor = "replica-b" if victim_name == "replica-a" else "replica-a"
        assert survivor in results  # the survivor picked up rerouted work
        # the dead replica was detected either by a live request taking the
        # connect-error reroute path or by the 0.05s health poller tripping
        # the breaker first — which one wins is a race, both are correct.
        # The streak may still be one failure short when the burst joins, so
        # drive poll cycles until the breaker opens instead of asserting a
        # single racy read
        deadline = time.monotonic() + 30.0
        breaker = None
        while time.monotonic() < deadline:
            breaker = router.stats()["replicas"][_rid(victim_srv)]["breaker"]
            if breaker == BREAKER_OPEN:
                break
            router.membership.poll_all()
            time.sleep(0.05)
        assert breaker == BREAKER_OPEN


def _rid(server: InferenceServer) -> str:
    from prime_tpu.serve.fleet.membership import replica_id_for

    return replica_id_for(server.url)


# ---- drain ------------------------------------------------------------------


class StreamingBackend(FleetBackend):
    """Backend with true live streaming: deltas trickle out so a drain can
    land mid-stream."""

    def __init__(self, name: str, n_deltas: int = 6, delta_s: float = 0.05):
        super().__init__(name)
        self.n_deltas = n_deltas
        self.delta_s = delta_s
        self.first_delta = threading.Event()

    def submit_text(self, prompt, max_new_tokens, temperature, top_p=1.0, templated=False):
        if self.submit_error is not None:
            raise self.submit_error
        self.calls.append(prompt)
        return object()

    def stream_text(self, req, timeout=None):
        for i in range(self.n_deltas):
            self.first_delta.set()
            time.sleep(self.delta_s)
            yield f"{self.name}:{i} "


def test_drain_completes_inflight_stream_and_reroutes_new_work():
    a = StreamingBackend("replica-a")
    b = StreamingBackend("replica-b")
    with make_fleet([a, b]) as (router, servers):
        probe = chat(router.url, f"{PREAMBLE} probe").json()
        victim_name = probe["choices"][0]["message"]["content"].split(":")[0]
        victim_idx = 0 if victim_name == "replica-a" else 1
        victim_srv = servers[victim_idx]
        victim_backend = (a, b)[victim_idx]
        victim_backend.first_delta.clear()

        deltas: list[str] = []
        done = threading.Event()

        def consume() -> None:
            with httpx.stream(
                "POST",
                f"{router.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": f"{PREAMBLE} stream"}],
                      "stream": True},
                timeout=30,
            ) as response:
                assert response.status_code == 200
                for line in response.iter_lines():
                    if line.startswith("data:") and "[DONE]" not in line:
                        deltas.append(line)
            done.set()

        t = threading.Thread(target=consume)
        t.start()
        assert victim_backend.first_delta.wait(timeout=10)  # stream is live
        # drain the replica mid-stream through the router's admin surface
        response = httpx.post(
            f"{router.url}/admin/drain", params={"replica": _rid(victim_srv)}, timeout=5
        )
        assert response.status_code == 200
        # the in-flight stream must run to completion (drain != kill)
        assert done.wait(timeout=30)
        t.join(timeout=5)
        payloads = [d for d in deltas if victim_name in d]
        assert len(payloads) >= victim_backend.n_deltas  # every delta arrived
        # the drained replica reports 503/draining on its own healthz...
        health = httpx.get(f"{victim_srv.url}/healthz", timeout=5)
        assert health.status_code == 503
        assert health.json()["state"] == "draining"
        # ...refuses new work directly...
        assert chat(victim_srv.url, "direct").status_code == 503
        # ...and the router sends every new request to the survivor
        survivor = "replica-b" if victim_name == "replica-a" else "replica-a"
        for i in range(4):
            body = chat(router.url, f"{PREAMBLE} after-drain {i}").json()
            assert body["choices"][0]["message"]["content"].startswith(survivor)


# ---- admission control / 429 ------------------------------------------------


def test_router_admission_gate_429_with_retry_after():
    slow = FleetBackend("replica-a", delay=0.6)
    with make_fleet([slow], max_inflight=1, queue_wait_s=0.05) as (router, _servers):
        codes: list[int] = []
        headers: list[str | None] = []
        lock = threading.Lock()

        def worker() -> None:
            response = chat(router.url, f"{PREAMBLE} x", timeout=30)
            with lock:
                codes.append(response.status_code)
                headers.append(response.headers.get("Retry-After"))

        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
            time.sleep(0.1)  # guarantee overlap with the 0.6 s in-flight call
        for t in threads:
            t.join(timeout=30)
        assert codes.count(200) >= 1
        assert codes.count(429) >= 1
        rejected = [h for c, h in zip(codes, headers) if c == 429]
        assert all(h is not None and float(h) > 0 for h in rejected)
        assert router.stats()["admission_rejected"] >= 1


def test_upstream_429_fails_over_then_propagates():
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    a.submit_error = QueueFullError("full", retry_after=0.2)
    with make_fleet([a, b]) as (router, _servers):
        # one replica shedding load: the request lands on the other
        response = chat(router.url, f"{PREAMBLE} one-full")
        assert response.status_code == 200
        assert response.json()["choices"][0]["message"]["content"] == "replica-b"
        # the whole fleet shedding load: 429 + Retry-After reaches the client
        b.submit_error = QueueFullError("full", retry_after=0.2)
        response = chat(router.url, f"{PREAMBLE} all-full")
        assert response.status_code == 429
        # integer delta-seconds passthrough from the last replica's 429
        assert response.headers["Retry-After"] == "1"
        assert response.json()["error"]["retry_after"] == pytest.approx(0.2)
        assert router.stats()["reroutes"].get("upstream_429", 0) >= 1


def test_client_survives_router_backpressure(monkeypatch, tmp_path):
    """End-to-end satellite: engine-style 429s propagate through the router
    and the SDK's InferenceClient rides them out via Retry-After."""
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    monkeypatch.setenv("PRIME_API_KEY", "local")

    from prime_tpu.api.inference import InferenceClient
    from prime_tpu.core.config import Config

    flaky = FleetBackend("replica-a")
    attempts = {"n": 0}

    real_generate = flaky.generate

    def generate(prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        attempts["n"] += 1
        if attempts["n"] <= 2:
            raise QueueFullError("warming up", retry_after=0.05)
        return real_generate(prompts, max_new_tokens, temperature, top_p, templated)

    flaky.generate = generate
    with make_fleet([flaky]) as (router, _servers):
        client = InferenceClient(
            config=Config(), base_url=f"{router.url}/v1", max_429_retries=3
        )
        reply = client.chat_completion(
            "tiny-test", [{"role": "user", "content": f"{PREAMBLE} retry me"}]
        )
        assert reply["choices"][0]["message"]["content"] == "replica-a"
        assert attempts["n"] == 3  # two 429s ridden out, third attempt served


def test_client_gives_up_after_bounded_retries(monkeypatch, tmp_path):
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    monkeypatch.setenv("PRIME_API_KEY", "local")

    from prime_tpu.api.inference import InferenceClient
    from prime_tpu.core.config import Config
    from prime_tpu.core.exceptions import RateLimitError

    full = FleetBackend("replica-a")
    full.submit_error = QueueFullError("permanently full", retry_after=0.02)
    with make_fleet([full]) as (router, _servers):
        client = InferenceClient(
            config=Config(), base_url=f"{router.url}/v1", max_429_retries=1
        )
        with pytest.raises(RateLimitError) as excinfo:
            client.chat_completion("tiny-test", [{"role": "user", "content": "x"}])
        assert excinfo.value.retry_after is not None


# ---- router surface ---------------------------------------------------------


def test_router_healthz_metrics_and_admin_surfaces():
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, servers):
        health = httpx.get(f"{router.url}/healthz", timeout=5)
        assert health.status_code == 200
        assert health.json()["routable"] == 2
        fleet = httpx.get(f"{router.url}/admin/fleet", timeout=5).json()
        assert set(fleet["replicas"]) == {_rid(servers[0]), _rid(servers[1])}
        models = httpx.get(f"{router.url}/v1/models", timeout=5).json()
        assert models["data"][0]["id"] == "tiny-test"
        registry = httpx.get(
            f"{router.url}/metrics", params={"format": "registry"}, timeout=5
        ).json()
        assert "fleet_requests_total" in registry["router"]
        assert httpx.get(f"{router.url}/nope", timeout=5).status_code == 404


def test_router_join_registers_new_replica():
    a = FleetBackend("replica-a")
    with make_fleet([a]) as (router, _servers):
        late = InferenceServer("tiny-test", FleetBackend("replica-late"), port=0).start()
        try:
            response = httpx.post(
                f"{router.url}/admin/join", json={"url": late.url}, timeout=5
            )
            assert response.status_code == 200
            assert response.json()["joined"] == _rid(late)
            assert _rid(late) in router.stats()["replicas"]
        finally:
            late.stop()


def test_router_forwards_attribution_headers():
    """X-PI-Job-Id / Authorization etc. must survive the proxy hop — a
    production upstream authorizes and attributes on them."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    seen: dict[str, str] = {}

    class Upstream(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, payload: dict) -> None:
            body = _json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._send({"state": "ready", "queue_depth": 0, "active_slots": 0})

        def do_POST(self):
            seen.update({k: v for k, v in self.headers.items()})
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self._send({"choices": [{"message": {"content": "ok"}}]})

    upstream = ThreadingHTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=upstream.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{upstream.server_address[1]}"
    router = serve_fleet([url], poll_interval=0.05, model_id="tiny-test")
    try:
        response = httpx.post(
            f"{router.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
            headers={"X-PI-Job-Id": "job-7", "Authorization": "Bearer tok"},
            timeout=10,
        )
        assert response.status_code == 200
        assert seen.get("X-PI-Job-Id") == "job-7"
        assert seen.get("Authorization") == "Bearer tok"
        # hop-by-hop/host were rewritten for the upstream connection
        assert seen.get("Host", "").endswith(str(upstream.server_address[1]))
    finally:
        router.stop()
        upstream.shutdown()
        upstream.server_close()


def test_admin_surface_token_gate_and_join_validation():
    a = FleetBackend("replica-a")
    with make_fleet([a], admin_token="sekrit") as (router, servers):
        assert chat(router.url, "x").status_code == 200  # data plane open
        rid = _rid(servers[0])
        denied = httpx.post(
            f"{router.url}/admin/drain", params={"replica": rid}, timeout=5
        )
        assert denied.status_code == 403
        auth = {"Authorization": "Bearer sekrit"}
        # malformed join payloads answer 400, not a dropped connection
        bad = httpx.post(
            f"{router.url}/admin/join", json={"url": 123}, headers=auth, timeout=5
        )
        assert bad.status_code == 400
        ok = httpx.post(
            f"{router.url}/admin/drain", params={"replica": rid}, headers=auth, timeout=5
        )
        assert ok.status_code == 200


def test_router_healthz_unavailable_when_all_replicas_down():
    a = FleetBackend("replica-a")
    with make_fleet([a], fail_threshold=1, cooldown=30.0) as (router, servers):
        servers[0].stop()
        # one failed request trips the breaker (threshold 1)
        assert chat(router.url, "x").status_code == 503
        health = httpx.get(f"{router.url}/healthz", timeout=5)
        assert health.status_code == 503
        assert health.json()["state"] == "unavailable"


# ---- distributed tracing & flight recorder ----------------------------------


def test_traced_fleet_single_trace_id_and_exposition_lint(tmp_path):
    """Tentpole acceptance over real HTTP: one traced request through a
    2-replica fleet leaves router-hop AND replica spans sharing the inbound
    trace id, parented across the hop — and both processes' Prometheus
    endpoints pass the exposition lint. (This is the CI serve-smoke traced
    request; PRIME_TRACE in the job environment exercises the import-time
    sink path too.)"""
    import json

    from prime_tpu.analysis.obs_contract import load_metrics_catalog
    from prime_tpu.obs import TRACER, lint_prometheus_text
    from prime_tpu.obs.trace import new_traceparent, parse_traceparent

    catalog = load_metrics_catalog(
        (Path(__file__).parent.parent / "docs" / "observability.md").read_text()
    )
    sink = tmp_path / "fleet-trace.jsonl"
    prev = TRACER.reconfigure(enabled=True, sink_path=str(sink))
    try:
        a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
        with make_fleet([a, b]) as (router, servers):
            header = new_traceparent()
            ctx = parse_traceparent(header)
            response = httpx.post(
                f"{router.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": f"{PREAMBLE} traced"}]},
                headers={"traceparent": header},
                timeout=30,
            )
            assert response.status_code == 200
            for url in (router.url, servers[0].url, servers[1].url):
                text = httpx.get(
                    f"{url}/metrics", params={"format": "prometheus"}, timeout=5
                ).text
                assert lint_prometheus_text(text, catalog=catalog) == [], (url, text)
    finally:
        TRACER.reconfigure(**prev)
    spans = [json.loads(line) for line in sink.read_text().splitlines()]
    # the observatory's periodic fleet.observe spans are PROCESS-scoped
    # (each poll cycle roots its own trace, like serve.dispatch on an
    # engine) — the one-trace-id pin below is about the REQUEST's spans
    assert any(s["name"] == "fleet.observe" for s in spans)
    spans = [s for s in spans if s["name"] != "fleet.observe"]
    by_name = {s["name"]: s for s in spans}
    assert {"fleet.route", "fleet.attempt", "serve.chat"} <= set(by_name)
    # ONE trace id, router to replica, under the client's inbound context
    assert {s["trace_id"] for s in spans} == {ctx.trace_id}
    assert by_name["fleet.route"]["parent_id"] == ctx.span_id
    assert by_name["fleet.attempt"]["parent_id"] == by_name["fleet.route"]["span_id"]
    assert by_name["serve.chat"]["parent_id"] == by_name["fleet.attempt"]["span_id"]


def test_untraced_fleet_still_propagates_ids_for_flight_recorder():
    """With tracing off (the default), the router still forwards/generates a
    traceparent so the router and replica flight recorders key the same id —
    including when the client spells the header 'Traceparent' (header names
    are case-insensitive; the router must match any casing and forward
    exactly one copy)."""
    from prime_tpu.obs.trace import new_traceparent, parse_traceparent

    a = FleetBackend("replica-a")
    with make_fleet([a]) as (router, servers):
        header = new_traceparent()
        response = httpx.post(
            f"{router.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": f"{PREAMBLE} x"}]},
            headers={"Traceparent": header},
            timeout=30,
        )
        assert response.status_code == 200
        recent = httpx.get(f"{router.url}/debug/requests", timeout=5).json()[
            "router"
        ]["recent"]
        assert recent, "router recorded no timeline"
        trace_id = recent[0]["trace_id"]
        assert trace_id == parse_traceparent(header).trace_id
        # one W3C trace id may cover several requests (a traced client fans
        # out, reusing the trace id with distinct parent span ids): each gets
        # its OWN timeline, not a conflated one
        sibling = f"00-{trace_id}-{'c' * 16}-01"
        assert (
            httpx.post(
                f"{router.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": f"{PREAMBLE} y"}]},
                headers={"traceparent": sibling},
                timeout=30,
            ).status_code
            == 200
        )
        recent = httpx.get(f"{router.url}/debug/requests", timeout=5).json()[
            "router"
        ]["recent"]
        same_trace = [e for e in recent if e["trace_id"] == trace_id]
        assert len(same_trace) == 2
        assert len({e["id"] for e in same_trace}) == 2
        replica_view = httpx.get(
            f"{servers[0].url}/debug/requests/{trace_id}", timeout=5
        )
        assert replica_view.status_code == 200
        assert replica_view.json()["trace_id"] == trace_id


def test_debug_requests_router_merges_replica_timeline():
    """GET /debug/requests/{id} on the router returns its hop timeline AND
    the serving replica's own view of the same trace id."""
    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, _servers):
        assert chat(router.url, f"{PREAMBLE} merge me").status_code == 200
        listing = httpx.get(f"{router.url}/debug/requests", timeout=5).json()
        entry = listing["router"]["recent"][0]
        assert entry["outcome"] == "ok" and entry["replica"]
        merged = httpx.get(
            f"{router.url}/debug/requests/{entry['id']}", timeout=5
        ).json()
        events = [e["event"] for e in merged["router"]["events"]]
        assert events[0] == "admitted" and "forwarded" in events
        assert merged["replica"] is not None
        assert merged["replica"]["trace_id"] == entry["trace_id"]
        missing = httpx.get(f"{router.url}/debug/requests/feedbeef", timeout=5)
        assert missing.status_code == 404


def test_debug_requests_auth_parity_with_admin_token():
    """Satellite: /debug/requests honors the same --admin-token gate as the
    admin surface, on the router and on the replica."""
    a = FleetBackend("replica-a")
    servers = [InferenceServer("tiny-test", a, port=0, admin_token="sekrit").start()]
    from prime_tpu.serve.fleet import serve_fleet as _serve_fleet

    router = _serve_fleet(
        [servers[0].url], poll_interval=0.05, model_id="tiny-test",
        admin_token="sekrit",
    )
    try:
        assert chat(router.url, f"{PREAMBLE} x").status_code == 200  # data plane open
        for url in (router.url, servers[0].url):
            assert httpx.get(f"{url}/debug/requests", timeout=5).status_code == 403
            ok = httpx.get(
                f"{url}/debug/requests",
                headers={"Authorization": "Bearer sekrit"},
                timeout=5,
            )
            assert ok.status_code == 200
        # the router's replica proxy carries the shared token: the merged
        # view works even though the replica gates /debug
        entry = httpx.get(
            f"{router.url}/debug/requests",
            headers={"Authorization": "Bearer sekrit"}, timeout=5,
        ).json()["router"]["recent"][0]
        merged = httpx.get(
            f"{router.url}/debug/requests/{entry['id']}",
            headers={"Authorization": "Bearer sekrit"}, timeout=5,
        ).json()
        assert merged["replica"] is not None
    finally:
        router.stop()
        servers[0].stop()


def test_serve_metrics_cli_against_fleet_router():
    """Satellite: `prime serve metrics --url <router>` renders the router's
    registry (fleet_requests_total, breaker gauges, affinity ratio) without
    KeyErrors, plus the per-replica routing summary; --debug-url renders the
    flight-recorder view."""
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    a, b = FleetBackend("replica-a"), FleetBackend("replica-b")
    with make_fleet([a, b]) as (router, _servers):
        for i in range(3):
            assert chat(router.url, f"{PREAMBLE} cli {i}").status_code == 200
        runner = CliRunner()
        out = runner.invoke(serve_cmd, ["metrics", "--url", router.url, "--plain"])
        assert out.exit_code == 0, out.output
        for needle in (
            "fleet_requests_total", "fleet_breaker_state",
            "fleet_affinity_hit_ratio",
        ):
            assert needle in out.output
        # the per-replica routing summary table rendered (breaker + outcomes)
        assert "closed" in out.output and "ok=3" in out.output
        debug = runner.invoke(
            serve_cmd, ["metrics", "--debug-url", router.url, "--plain"]
        )
        assert debug.exit_code == 0, debug.output
        assert "forwarded" in debug.output or "ok" in debug.output
        # one-request timeline mode
        import json as _json

        rid = httpx.get(f"{router.url}/debug/requests", timeout=5).json()[
            "router"
        ]["recent"][0]["id"]
        one = runner.invoke(
            serve_cmd,
            ["metrics", "--debug-url", router.url, "--request", rid, "--plain"],
        )
        assert one.exit_code == 0, one.output
        assert "admitted" in one.output and "--- router:" in one.output
        as_json = runner.invoke(
            serve_cmd,
            ["metrics", "--debug-url", router.url, "--output", "json"],
        )
        assert as_json.exit_code == 0
        assert "router" in _json.loads(as_json.output)
