"""Availability + pods clients against the in-process fake control plane.

This is the hermetic test layer SURVEY.md §4 calls for: no monkeypatched
client methods — the real clients talk to a stateful fake backend through the
real transport code path.
"""

import pytest

from prime_tpu.api.availability import AvailabilityClient
from prime_tpu.api.pods import CreatePodRequest, PodsClient
from prime_tpu.core.client import APIClient
from prime_tpu.core.config import Config
from prime_tpu.core.exceptions import UnauthorizedError, ValidationError
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake():
    return FakeControlPlane(pod_ready_after_polls=2)


@pytest.fixture
def client(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    return APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)


def test_auth_enforced(fake):
    cfg = Config()
    cfg.api_key = "wrong-key"
    bad = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    with pytest.raises(UnauthorizedError):
        AvailabilityClient(bad).list_tpus()


def test_list_tpus_filters_and_slice_metadata(client):
    avail = AvailabilityClient(client)
    offers = avail.list_tpus(tpu_type="v5e", min_chips=8, spot=False)
    assert offers and all(o.tpu_type == "v5e" and o.chips >= 8 and not o.spot for o in offers)
    v5e8 = [o for o in offers if o.slice_name == "v5e-8"][0]
    assert v5e8.hosts == 1 and v5e8.ici_topology == "2x4"
    assert v5e8.spec.chips == 8
    v5e16 = [o for o in offers if o.slice_name == "v5e-16"][0]
    assert v5e16.hosts == 2 and v5e16.dcn_pool  # multi-host rides a DCN pool
    # price sanity: per-chip price constant within a generation
    assert abs(v5e8.price_per_chip_hour - v5e16.price_per_chip_hour) < 1e-6


def test_list_tpus_pagination_walks_all_pages(fake, client):
    avail = AvailabilityClient(client)
    all_offers = avail.list_tpus()
    assert len(all_offers) == len(fake.offers)
    # multiple GET pages were issued
    pages = [p for m, p in fake.requests if m == "GET" and "availability/tpus" in p]
    assert len(pages) >= 2


def test_multi_host_filter(client):
    avail = AvailabilityClient(client)
    multi = avail.list_tpus(tpu_type="v5p", multi_host=True)
    assert multi and all(o.hosts > 1 for o in multi)


def test_tpu_types_catalog(client):
    types = AvailabilityClient(client).list_tpu_types()
    names = {t["tpuType"] for t in types}
    assert {"v4", "v5e", "v5p", "v6e"} <= names


def test_pod_lifecycle_multi_host_ssh(fake, client):
    pods = PodsClient(client)
    pod = pods.create(CreatePodRequest(name="train-16", slice_name="v5e-16"))
    assert pod.status == "PENDING"
    assert pod.hosts == 2 and pod.ici_topology == "4x4"

    s1 = pods.get_status(pod.pod_id)
    assert s1.status == "PROVISIONING" and s1.ssh_connections is None
    s2 = pods.get_status(pod.pod_id)
    assert s2.status == "ACTIVE"
    # one SSH endpoint per worker host (the slice spans 2 hosts)
    assert s2.ssh_connections is not None and len(s2.ssh_connections) == 2

    listed = pods.list()
    assert [p.pod_id for p in listed] == [pod.pod_id]

    pods.terminate(pod.pod_id)
    assert pods.list() == []
    hist = pods.history()
    assert hist[0].pod_id == pod.pod_id and hist[0].status == "TERMINATED"


def test_pod_create_invalid_slice_is_422_with_field(client):
    pods = PodsClient(client)
    with pytest.raises(ValidationError) as ei:
        pods.create(CreatePodRequest(name="x", slice_name="v5e-3"))
    msgs = ei.value.field_messages()
    assert msgs and "sliceName" in msgs[0]


def test_pod_team_auto_injection(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    cfg.team_id = "team_1"
    client = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    pod = PodsClient(client).create(CreatePodRequest(name="t", slice_name="v5e-1"))
    assert pod.team_id == "team_1"


def test_ssh_connection_normalization():
    from prime_tpu.api.pods import PodStatus

    assert PodStatus.model_validate({"podId": "p", "status": "ACTIVE", "sshConnections": [None]}).ssh_connections is None
    assert PodStatus.model_validate({"podId": "p", "status": "ACTIVE", "sshConnections": "root@h:22"}).ssh_connections == ["root@h:22"]
    assert PodStatus.model_validate({"podId": "p", "status": "ACTIVE", "sshConnections": ["", "a"]}).ssh_connections == ["a"]
