"""Benchmark: TPU throughput on the north-star paths (BASELINE.md).

Sections (each prints a `# bench:` progress line; ONE final JSON line):
  headline   decode tokens/sec — llama3.2-1b bf16, batch 8, 128+128 greedy
  eval       eval samples/sec THROUGH EvalRunner (tokenize → batch → sharded
             generate → score → results.jsonl) — the BASELINE.json metric
  serve      continuous-batching engine tokens/sec under concurrent load
  quant      int8 weights / int8 KV variants of the headline
  longctx    flash-decode pallas kernel vs XLA at C=4096 (the regime the
             kernel was built for; short-context already dispatches to XLA)

The record is unlosable by construction (last-JSON-line-wins, so each print
below overwrites the one before): a provisional abort line prints BEFORE the
preflight (round 3's driver kill mid-preflight left parsed:null), the
structured abort with diagnosis prints on preflight failure, the headline
prints as soon as measured, and the enriched record prints last. The
preflight itself is bounded at ~7.5 min — each probe longer than the
tunnel's observed ~150 s success latency (rounds 1-2 undercut it and
recorded 0.0), total well under the driver's wall clock (round 3 overshot
it and recorded nothing).

The reference publishes no numbers (BASELINE.json "published": {}), so
vs_baseline is the ratio against PREV_DECODE_TOK_S — this repo's round-1
measured anchor.
"""

import functools
import json
import os
import subprocess
import sys
import time

# Round-1 anchor (v5e-1, this repo @ first bench). vs_baseline = value / this.
PREV_DECODE_TOK_S = 1396.6

# Record schema version. Schema 1: rounds 1-5 (implicit — no "schema" key;
# scripts/perf_delta.py labels them on load). Schema 2: serve/fleet sections
# are measured through prime_tpu.loadgen (registry-snapshot-derived numbers,
# "loadgen" SLO report key) and the preflight is backend-conditional.
SCHEMA_VERSION = 2

# TPU v5e single-chip peaks for the roofline fields (VERDICT r4 #2): decode
# is HBM-bound, so each section reports achieved GB/s and % of peak from a
# bytes-moved model (weights + KV + scales); prefill is MXU-bound, so the
# headline also reports prefill MFU against the bf16 peak.
V5E_HBM_GBS = 819.0
V5E_BF16_FLOPS = 1.97e14

# PRIME_BENCH_SMOKE=1 shrinks every section to tiny-model/tiny-shape so the
# full main() path (all sections, all record fields) can be validated on CPU
# in ~a minute before a bench.py change lands — the watcher may fire the real
# bench at any moment, so edits must never leave it broken.
def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").lower() not in ("", "0", "false", "no")


SMOKE = _env_flag("PRIME_BENCH_SMOKE")
if SMOKE:
    # run the pallas sections (longctx/winctx variants) in interpret mode so
    # an off-TPU smoke exercises the kernel dispatch paths end to end
    os.environ.setdefault("PRIME_TPU_PALLAS_INTERPRET", "1")
    # smoke validates bench.py's code paths, not the tunnel: force the CPU
    # backend and neutralize the axon plugin. Setting the env vars in-process
    # is too late (the axon site hook reads them at interpreter start, and a
    # down tunnel then blocks backend init forever — exactly when smoke gets
    # used), so re-exec once with a scrubbed environment.
    # the re-exec is for `python bench.py` runs ONLY: an importer (e.g.
    # scripts/serve_profile.py borrowing the serve scenario) must never have
    # its process silently replaced by a smoke bench
    if __name__ == "__main__" and os.environ.get("PRIME_BENCH_SMOKE_REEXEC") != "1":
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            PRIME_BENCH_SMOKE_REEXEC="1",
        )
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
BATCH = 2 if SMOKE else 8
PROMPT_LEN = 16 if SMOKE else 128
NEW_TOKENS = 8 if SMOKE else 128
MODEL = "tiny-test" if SMOKE else "llama3.2-1b"

# serve-section scenario, module-level so scripts/serve_profile.py profiles
# EXACTLY the workload the bench measures (tuning one tunes both)
SERVE_N_REQ, SERVE_NEW = (4, 8) if SMOKE else (16, 64)
SERVE_PROMPT_LEN = 24 if SMOKE else 96
SERVE_SLOTS = 8
SERVE_CHUNK = 8
SERVE_CAPACITY = 1024


def serve_prompts_for(config) -> list[list[int]]:
    """The serve scenario's deterministic prompt set (no shared prefixes
    between requests, so admissions exercise cold prefill)."""
    return [
        [1]
        + [(7 * (i + j)) % (config.vocab_size - 3) + 3 for j in range(SERVE_PROMPT_LEN)]
        for i in range(SERVE_N_REQ)
    ]

# Observed on the axon tunnel (scripts/tpu_watch.sh, round 3): a trivial
# matmul probe SUCCEEDS but takes ~150 s end-to-end (interpreter + PJRT
# handshake + first compile over the relay). Rounds 1-2 probed with a 120 s
# timeout and recorded the backend as "unresponsive" — the probe budget must
# comfortably exceed the success latency, not undercut it. Round 3's probe
# schedule (5 × 330 s + waits, ~35 min worst case) exceeded the DRIVER's
# budget instead: rc=124 with no JSON printed (BENCH_r03.json parsed:null).
# Both bounds matter: each probe > ~150 s success latency, total ≤ ~8 min.
# One retry with a LONGER budget (round 5's two 210 s probes both timed out;
# a marginal tunnel deserves one escalated attempt before the round aborts).
PROBE_TIMEOUTS_S = (210.0, 240.0)
PROBE_WAITS_S = (30.0,)  # between attempts; 210+30+240 = 8 min worst case


def _sweep_stray_holders() -> list[str]:
    """Kill leftover TPU-touching helper processes from the round so the
    bench (and the driver's end-of-round snapshot) owns the chip cleanly:
    the reachability watcher (scripts/tpu_watch.sh) and any orphaned probe
    interpreters. Never touches this process or its ancestors."""
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(10):
        try:
            with open(f"/proc/{pid}/stat") as f:
                # comm (field 2) may itself contain spaces/parens — ppid is
                # the 2nd field AFTER the last ')', not split()[3]
                after_comm = f.read().rsplit(")", 1)[1].split()
                pid = int(after_comm[1])
        except (OSError, ValueError, IndexError):
            break
        if pid <= 1:
            break
        ancestors.add(pid)
    killed = []
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,args"], capture_output=True, text=True, timeout=10
        ).stdout
    except Exception:
        return killed
    for line in out.splitlines()[1:]:
        parts = line.strip().split(None, 1)
        if len(parts) != 2:
            continue
        pid_s, cmd = parts
        try:
            pid = int(pid_s)
        except ValueError:
            continue
        if pid == me or pid in ancestors:
            continue
        # exact helper signatures only: the watcher's shell process (bash
        # running the script — NOT an editor/grep whose argv mentions it),
        # probe interpreters (python -c with the probe matmul literal), and
        # a CONCURRENT bench.py (the watcher's opportunistic capture — a
        # SIGKILL to its parent shell orphans the child, which would keep
        # holding the single-client chip through the driver's preflight)
        is_watcher = "bash" in cmd and cmd.rstrip().endswith("tpu_watch.sh")
        is_probe = "python" in cmd and "-c" in cmd and "jnp.ones((256" in cmd
        is_bench = "python" in cmd and cmd.rstrip().endswith("bench.py")
        if is_watcher or is_probe or is_bench:
            try:
                os.kill(pid, 9)
                killed.append(f"{pid}:{cmd[:60]}")
            except OSError:
                pass
    return killed


def _tree_bytes(params) -> int:
    """Total bytes of a parameter pytree as stored on device (bf16 weights
    2 bytes, int8 1 byte + fp scales; int4 weights are nibble-packed into
    uint8 carriers with half the elements, so itemsize covers them too)."""
    import jax

    return int(
        sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params))
    )


def _kv_bytes_per_slot(config, kv_bytes: float) -> float:
    """Bytes one cache slot (one token position, all layers, K+V) occupies.
    kv_bytes=2 for bf16 caches; int8 caches store 1 byte + a per-(token,head)
    fp32 scale amortized over head_dim (quantize_kv in models/llama.py:95)."""
    return config.n_layers * 2 * config.n_kv_heads * config.head_dim * kv_bytes


def _decode_roofline(
    param_bytes: int, config, batch: int, ctx_avg: float, steps: int,
    seconds: float, kv_bytes: float = 2.0, prefix: str = "",
) -> dict:
    """HBM roofline for a batched decode phase: every step streams the full
    weight set once (batch shares it) and each sequence reads its KV cache at
    the running context and writes one slot. Returns achieved GB/s and % of
    the v5e peak, keyed with `prefix` so sections can carry their own."""
    slot = _kv_bytes_per_slot(config, kv_bytes)
    per_step = param_bytes + batch * slot * (ctx_avg + 1)
    gbs = per_step * steps / seconds / 1e9
    return {
        f"{prefix}hbm_model_gb_per_step": round(per_step / 1e9, 4),
        f"{prefix}hbm_gbs": round(gbs, 1),
        f"{prefix}hbm_pct_peak": round(100.0 * gbs / V5E_HBM_GBS, 1),
    }


# The probe registers faulthandler on SIGUSR1 so a timed-out probe can be
# asked WHERE it is stuck (inside PJRT client init? the tunnel handshake?
# the compile RPC?) before being killed — BENCH_r05's two 210s timeouts
# produced nothing but "backend unresponsive", which is undiagnosable.
# NOTE: the `jnp.ones((256` literal is _sweep_stray_holders' probe signature.
PROBE_CODE = (
    "import faulthandler, signal, sys\n"
    "faulthandler.register(signal.SIGUSR1, file=sys.stderr)\n"
    "import jax, jax.numpy as jnp\n"
    "x = jnp.ones((256, 256))\n"
    "print(float(jnp.sum(x @ x)))\n"
)


def _probe_once(timeout_s: float, code: str = PROBE_CODE) -> dict | None:
    """One accelerator probe in a SUBPROCESS (fresh PJRT client — an
    in-process retry would reuse the same stuck client). None on success;
    on failure a dict with ``error`` and — for hangs — ``child_stacks``,
    the faulthandler dump of every thread in the stuck child."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        _, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        stacks = ""
        try:
            # ask the child to dump its thread stacks, give the write a
            # moment to land, THEN kill — the dump is the whole point
            proc.send_signal(signal.SIGUSR1)
            time.sleep(2.0)
        except OSError:
            pass
        proc.kill()
        try:
            _, stacks = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover — kill -9'd
            stacks = ""
        return {
            "error": f"backend unresponsive after {timeout_s:.0f}s",
            "child_stacks": (stacks or "").strip()[-3000:] or None,
        }
    if proc.returncode != 0:
        return {"error": f"probe rc={proc.returncode}: {err.strip()[-300:]}"}
    return None


def _diagnose() -> dict:
    """On preflight failure: enumerate candidate chip-holding processes and
    environment state so the record says WHY, not just 'unresponsive'."""
    # key NAMES only (plus the one known-safe platform selector): the failure
    # JSON lands in git via BENCH_rNN.json, so tunnel endpoints/credentials
    # that may ride AXON_* values must not be echoed
    info: dict = {
        "env_keys": sorted(k for k in os.environ if "AXON" in k or "JAX" in k),
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
    }
    # pid/age/comm plus the SCRIPT NAME only — full argv can carry tunnel
    # endpoints or tokens (e.g. `python -m tunnel --token=...`) and this JSON
    # is committed to git, but a bare "python" row made round 4's stuck-holder
    # postmortem unactionable. The basename of the first .py argument (or the
    # -m module name / a literal "-c") identifies the holder without exposing
    # a single flag value.
    def _script_of(pid: str) -> str:
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = [a.decode(errors="replace") for a in f.read().split(b"\0") if a]
        except OSError:
            return "?"
        for i, arg in enumerate(argv[1:], start=1):
            if arg == "-c":
                return "-c"
            if arg == "-m":
                return f"-m {argv[i + 1]}" if i + 1 < len(argv) else "-m"
            # ONLY a non-dash .py path is safe to echo: a bare argument may be
            # the space-separated VALUE of a preceding flag (`--token SECRET`)
            # and a dash-prefixed one is a flag (possibly `--config=creds.py`)
            if arg.endswith(".py") and not arg.startswith("-"):
                return os.path.basename(arg)
        return "?"

    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,etime,comm"], capture_output=True, text=True, timeout=10
        ).stdout
        info["python_procs"] = [
            " ".join(line.split()[:3]) + f" [{_script_of(line.split()[0])}]"
            for line in out.splitlines()[1:]
            if "python" in line
        ][:20]
    except Exception as e:
        info["python_procs"] = [f"ps failed: {e}"]
    try:
        out = subprocess.run(["ss", "-tln"], capture_output=True, text=True, timeout=10).stdout
        info["listen_ports"] = sorted(
            {
                line.split()[3].rsplit(":", 1)[-1]
                for line in out.splitlines()[1:]
                if len(line.split()) > 3
            }
        )[:10]
    except Exception:
        pass
    # newest flight-recorder summaries from any local serve/router process
    # (GET /debug/requests, docs/observability.md): when a serve replica is
    # what's holding the chip, its per-request timelines say what it was
    # doing — queued? mid-prefill? wedged mid-chunk? — when the backend
    # stopped answering. Loopback with a 1s budget per port; never fatal.
    flights: dict = {}
    try:
        import httpx

        for port in (info.get("listen_ports") or [])[:8]:
            try:
                response = httpx.get(
                    f"http://127.0.0.1:{port}/debug/requests", timeout=1.0
                )
                if response.status_code != 200:
                    continue
                data = response.json()
                data = data.get("router", data)  # router wraps its summaries
                if isinstance(data, dict) and ("recent" in data or "inflight" in data):
                    flights[str(port)] = {
                        "inflight": data.get("inflight", [])[:5],
                        "recent": data.get("recent", [])[:5],
                    }
            except Exception:  # noqa: BLE001 — diagnosis must never throw
                continue
    except Exception:  # noqa: BLE001 — httpx may be absent in minimal envs
        pass
    if flights:
        info["flight_recorders"] = flights
    return info


def _latest_opportunistic_record() -> tuple[str, dict] | None:
    """Newest committed BENCH_opportunistic_r*.json with a real headline —
    the reachability watcher's capture from an earlier window of the same
    (or a previous) round. A failed preflight carries it forward, clearly
    labeled, instead of zeroing the round's record (round 5: the watcher
    measured 1602 tok/s hours before the driver's probes found the tunnel
    down, and the round still recorded 0.0)."""
    import glob

    best: tuple[float, str, dict] | None = None
    for path in glob.glob("BENCH_opportunistic_r*.json"):
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(data, dict) and isinstance(data.get("value"), (int, float)):
            # label the record's schema era explicitly (absent key = schema 1,
            # the pre-loadgen rounds) so perf_delta.py and the carry-forward
            # below never guess which fields can exist in it
            data.setdefault("schema", 1)
            # newest by mtime, NOT lexicographic path order (r10 sorts
            # before r9 and would resurrect a stale round's number)
            if data["value"] > 0 and (best is None or mtime > best[0]):
                best = (mtime, path, data)
    return (best[1], best[2]) if best else None


def _cpu_only_backend() -> bool:
    """True when this run is pinned to CPU (JAX_PLATFORMS=cpu — CI, the
    loadgen smoke, a laptop). The axon-tunnel preflight exists to detect a
    wedged TPU backend; on a CPU run it can only produce a false abort, so
    the preflight is conditional on actually expecting an accelerator.
    JAX_PLATFORMS is priority-ordered: only a CPU-FIRST list counts —
    "tpu,cpu" (TPU preferred, CPU fallback) still wants the probe."""
    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    return platforms.split(",")[0].strip() == "cpu"


def _preflight() -> dict:
    # PRIME_BENCH_NO_SWEEP: the watcher's opportunistic bench sets this —
    # its probe just confirmed the tunnel is UP, so there are no stray
    # holders to clear, and sweeping would race the DRIVER's authoritative
    # bench (whichever swept last would SIGKILL the other mid-run)
    no_sweep = _env_flag("PRIME_BENCH_NO_SWEEP")
    # Provisional abort record FIRST, before anything that can hang or be
    # killed: the driver takes the LAST JSON line on stdout, so a later
    # success (or the structured abort below) overwrites this — but an
    # external kill at ANY point now leaves a parseable record instead of
    # round 3's parsed:null.
    print(
        json.dumps(
            {
                "schema": SCHEMA_VERSION,
                "metric": "decode_tokens_per_sec (bench killed before preflight verdict)",
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": "provisional record: process was killed before the "
                "preflight finished; see # bench: lines above for progress",
                "backend": os.environ.get("JAX_PLATFORMS", "unknown"),
            }
        ),
        flush=True,
    )
    swept = [] if no_sweep else _sweep_stray_holders()
    if swept:
        print(f"# bench: swept {len(swept)} stray TPU helper(s): {swept}", flush=True)
    # per-probe structured report: every attempt's timeout/elapsed/reason
    # lands in the record's "preflight" section on failure, so a dead round
    # says WHICH probe failed HOW instead of one flattened error string
    report: dict = {"ok": False, "probes": []}
    for attempt, timeout_s in enumerate(PROBE_TIMEOUTS_S):
        t0 = time.monotonic()
        result = _probe_once(timeout_s)
        reason = None if result is None else result["error"]
        entry = {
            "attempt": attempt + 1,
            "timeout_s": timeout_s,
            "elapsed_s": round(time.monotonic() - t0, 1),
            "error": reason,
        }
        if result is not None and result.get("child_stacks"):
            # the stuck child's own thread stacks (faulthandler): the
            # difference between "tunnel down" and "compile RPC wedged"
            entry["child_stacks"] = result["child_stacks"]
        report["probes"].append(entry)
        if reason is None:
            report["ok"] = True
            failed = attempt
            print(
                f"# bench: preflight ok in {time.monotonic() - t0:.0f}s"
                + (f" after {failed} failed probe(s)" if failed else ""),
                flush=True,
            )
            return report
        print(
            f"# bench: preflight probe {attempt + 1}/{len(PROBE_TIMEOUTS_S)} "
            f"failed: {reason}",
            flush=True,
        )
        if attempt < len(PROBE_WAITS_S):
            time.sleep(PROBE_WAITS_S[attempt])
    report["diagnosis"] = _diagnose()
    record = {
        "schema": SCHEMA_VERSION,
        "metric": "decode_tokens_per_sec (bench aborted)",
        "value": 0.0,
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "error": f"preflight failed: {report['probes'][-1]['error']}",
        "preflight": report,
        # NOTE: not jax.default_backend() — that query can hang on
        # the same stuck backend this preflight is detecting
        "backend": os.environ.get("JAX_PLATFORMS", "unknown"),
    }
    # don't zero the round when the watcher already measured it: carry the
    # opportunistic capture forward with explicit provenance
    fallback = _latest_opportunistic_record()
    if fallback is not None:
        path, stale = fallback
        record.update(
            {
                "metric": stale.get("metric", "decode_tokens_per_sec")
                + " [carried forward: preflight failed]",
                "value": stale["value"],
                "unit": stale.get("unit", "tokens/s"),
                "vs_baseline": stale.get("vs_baseline", 0.0),
                "carried_from": path,
                # the donor's own era, so a schema-2 consumer knows whether
                # the carried fields follow schema-1 (pre-loadgen) shape
                "carried_schema": stale.get("schema", 1),
            }
        )
        print(f"# bench: carrying forward {path} (value {stale['value']})", flush=True)
    print(json.dumps(record), flush=True)  # os._exit below skips the stdio flush
    # os._exit: a hung PJRT client can block normal interpreter teardown
    os._exit(1)


def main() -> None:
    # Smoke mode validates bench.py's own code paths, not the tunnel: skip
    # the preflight entirely — its sweep would SIGKILL the live watcher (and
    # any in-flight opportunistic bench), and its probes would burn ~7.5 min
    # exiting(1) whenever the tunnel is down, which is exactly when smoke runs.
    # A CPU-pinned run (CI loadgen smoke, laptop) skips it too: the axon
    # probe can only false-abort a run that never wanted the accelerator.
    if SMOKE or _cpu_only_backend():
        preflight_report = None
        if _cpu_only_backend() and not SMOKE:
            print("# bench: CPU backend pinned — axon preflight skipped", flush=True)
    else:
        preflight_report = _preflight()
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.models.sampler import generate

    config = get_config(MODEL)
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, config, dtype=jnp.bfloat16)
    prompts = jax.random.randint(jax.random.PRNGKey(1), (BATCH, PROMPT_LEN), 1, config.vocab_size)
    lengths = jnp.full((BATCH,), PROMPT_LEN, dtype=jnp.int32)

    def time_fn(fn, iterations: int = 3) -> float:
        """Best wall-clock seconds over `iterations` (after one warmup/compile
        call). fn must end with a scalar host fetch: on tunneled backends
        (axon) block_until_ready returns before the computation has run."""
        fn()  # warmup + compile
        best_s = float("inf")
        for _ in range(iterations):
            t0 = time.perf_counter()
            fn()
            best_s = min(best_s, time.perf_counter() - t0)
        return best_s

    def time_op(op, q0, operands, iters=None) -> float:
        """Per-invocation seconds for a decode-shaped op, with dispatch and
        tunnel-transfer overhead cancelled out: jit a fori_loop that feeds the
        op's output back into the query (the data dependency serializes the
        chain), time a short and a long chain, and attribute the difference
        to the extra iterations. Operands ride as jit ARGUMENTS — a closure
        constant is re-shipped by a tunneled backend on every call, which made
        the first cut of these microbenches report tunnel RTT (~32 ms/op,
        0.5 GB/s "achieved") instead of kernel time. ``op(q, *operands)``
        must return an array of q's shape."""
        if iters is None:
            # smoke runs pallas in interpret mode where every chained
            # iteration costs milliseconds — keep the chains token-length
            iters = (2, 12) if SMOKE else (10, 510)

        @functools.partial(jax.jit, static_argnames=("n",))
        def chain(q, ops, n):
            def body(_, q_cur):
                # the tiny scaled add keeps values bounded across 500 hops
                # while making every iteration depend on the previous one
                return q_cur + op(q_cur, *ops) * 1e-6
            return jax.lax.fori_loop(0, n, body, q)

        short, long_ = iters
        t_short = time_fn(
            lambda: float(jnp.sum(chain(q0, operands, short))), iterations=3
        )
        t_long = time_fn(
            lambda: float(jnp.sum(chain(q0, operands, long_))), iterations=3
        )
        if t_long <= t_short:
            # timing noise inverted the chains: a silently-floored difference
            # would record a ~10^6x phantom speedup as if it were real
            raise RuntimeError(
                f"timing inversion (t_long {t_long:.4f}s <= t_short "
                f"{t_short:.4f}s): backend too noisy for this microbench"
            )
        return (t_long - t_short) / (long_ - short)

    def run_generate(**kw):
        result = generate(
            params, prompts, lengths, config, jax.random.PRNGKey(2),
            max_new_tokens=NEW_TOKENS, temperature=0.0, **kw,
        )
        float(jnp.sum(result.tokens))

    # ---- headline ------------------------------------------------------------
    best = time_fn(run_generate)
    decode_tok_s = BATCH * NEW_TOKENS / best
    param_bytes = _tree_bytes(params)
    record = {
        "schema": SCHEMA_VERSION,
        "metric": f"decode_tokens_per_sec ({MODEL} bf16, b{BATCH}, p{PROMPT_LEN}+{NEW_TOKENS})",
        "value": round(decode_tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(decode_tok_s / PREV_DECODE_TOK_S, 3),
        "gen_time_s": round(best, 3),
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "param_gb": round(param_bytes / 1e9, 3),
    }
    if preflight_report is not None:
        record["preflight"] = preflight_report  # per-probe timings/diagnostics
    # early print: an external kill mid-extras still leaves a nonzero record
    print(json.dumps(record), flush=True)

    # roofline: time the prefill alone (MXU-bound → MFU), then attribute the
    # remaining gen time to the decode loop (HBM-bound → achieved GB/s). The
    # FLOP model is the standard causal count: 2·N_params per token plus
    # 2·layers·heads·S²·head_dim for the score/value matmuls.
    try:
        from prime_tpu.models.llama import forward, init_cache

        prefill_cache = init_cache(config, BATCH, PROMPT_LEN + NEW_TOKENS)
        # params/cache as ARGUMENTS, not closure captures: a captured tree is
        # serialized into the program as 2.47 GB of constants, which a
        # tunneled backend re-ships on compile (observed stalling the r5
        # opportunistic capture for minutes)
        prefill_fn = jax.jit(
            lambda p, c: forward(p, prompts, config, cache=c)[0]
        )
        prefill_s = time_fn(
            lambda: float(jnp.sum(prefill_fn(params, prefill_cache))), iterations=3
        )
        n_params = param_bytes / 2  # bf16 storage
        prefill_flops = (
            2.0 * n_params * BATCH * PROMPT_LEN
            + 2.0 * config.n_layers * config.n_heads
            * BATCH * PROMPT_LEN**2 * config.head_dim
        )
        record["prefill_time_ms"] = round(prefill_s * 1e3, 2)
        record["prefill_mfu_pct"] = round(
            100.0 * prefill_flops / prefill_s / V5E_BF16_FLOPS, 1
        )
        # only attribute decode time when the residual is clearly above
        # measurement noise — prefill_s comes from a different jitted call,
        # and a clamped near-zero residual would commit absurd GB/s numbers
        decode_s = best - prefill_s
        if decode_s > 0.2 * best:
            record.update(
                _decode_roofline(
                    param_bytes, config, BATCH, PROMPT_LEN + NEW_TOKENS / 2,
                    NEW_TOKENS, decode_s,
                )
            )
            record["decode_only_tok_s"] = round(BATCH * NEW_TOKENS / decode_s, 1)
            print(
                f"# bench: roofline prefill mfu {record['prefill_mfu_pct']}% | "
                f"decode {record['hbm_gbs']} GB/s ({record['hbm_pct_peak']}% of "
                f"v5e HBM peak)",
                flush=True,
            )
        else:
            record["roofline_note"] = (
                "decode residual below noise (prefill ~ gen time); "
                "decode-only attribution skipped"
            )
    except Exception as e:  # noqa: BLE001 — roofline must not zero the headline
        record["roofline_error"] = f"{type(e).__name__}: {e}"[:200]
    print(json.dumps(record), flush=True)

    # ---- eval: the north-star metric through the REAL runner ----------------
    # EvalRunner end to end: tokenizer encode, batch assembly (+ SPMD padding),
    # sharded generate on a 1-device mesh, scoring, results.jsonl writes —
    # the BASELINE.json "verifiers eval samples/sec" definition, not a proxy.
    try:
        import tempfile

        from prime_tpu.evals.runner import EvalRunSpec, JaxGenerator, run_eval

        eval_gen = JaxGenerator(MODEL, slice_name="v5e-1")
        with tempfile.TemporaryDirectory() as td:
            spec = EvalRunSpec(
                env="synthetic-arith",
                model=MODEL,
                limit=8 if SMOKE else 32,
                batch_size=4 if SMOKE else 8,
                max_new_tokens=16 if SMOKE else 64,
                output_dir=td,
            )
            run_eval(spec, generator=eval_gen)  # warmup: compile + first batch shapes
            result = run_eval(spec, generator=eval_gen)
        record["eval_samples_per_sec"] = round(result.metrics["samples_per_sec"], 2)
        record["eval_wall_time_s"] = round(result.metrics["wall_time_s"], 2)
        print(f"# bench: eval {record['eval_samples_per_sec']} samples/s", flush=True)
        del eval_gen
    except Exception as e:  # noqa: BLE001 — a failed extra must not zero the headline
        record["eval_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: eval section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve: continuous-batching engine under concurrent load ------------
    # Measured THROUGH prime_tpu.loadgen (schema 2): the prompt sets and
    # engine configs are unchanged from schema 1, but the measured window is
    # driven by the loadgen runner and every number comes from registry
    # snapshot deltas (captured_at-bracketed) instead of a client stopwatch.
    # Each section's RunResult also lands in the record's "loadgen" SLO
    # report — the same artifact the CI smoke publishes.
    from prime_tpu.loadgen import (
        EngineTarget,
        build_report,
        run_schedule,
        scenario_row,
        schedule_from_prompts,
    )

    n_req, req_new = SERVE_N_REQ, SERVE_NEW
    serve_prompt_len = SERVE_PROMPT_LEN
    serve_slots = SERVE_SLOTS
    serve_prompts = serve_prompts_for(config)
    loadgen_results: list = []
    # scenario rows produced by self-contained comparisons (the disagg
    # section builds its own HTTP fleets and returns finished rows): appended
    # to the SLO report's scenarios without touching its headline
    loadgen_rows_extra: list = []

    def run_serve(
        kv_quant: bool = False, speculative: bool = False, prompts=None,
        record_counters: bool = False, obs_key: str | None = None,
        scenario: str = "serve", mesh_config="", max_new: int | None = None,
    ) -> dict:
        """Drive one engine configuration through the loadgen runner and
        return the registry-windowed SLO row (tok/s, TPOT quantiles,
        accept ratio, ...)."""
        from prime_tpu.serve.engine import ContinuousBatchingEngine

        prompts = prompts or serve_prompts
        new_tokens = max_new if max_new is not None else req_new
        engine = ContinuousBatchingEngine(
            params, config, pad_id=0, max_slots=serve_slots,
            capacity=SERVE_CAPACITY, chunk=SERVE_CHUNK,
            kv_quant=kv_quant, speculative=speculative, mesh_config=mesh_config,
        )
        try:
            # warmup: compile prefill/decode/finalize for the buckets in play.
            # TWO passes over the same prompt: the second admission hits the
            # prompt-prefix KV cache and prefills only the suffix — a
            # DIFFERENT chunk shape whose first compile would otherwise land
            # mid-measurement (remote TPU compiles cost seconds each)
            for _ in range(2):
                warm = engine.submit(prompts[0], max_new_tokens=new_tokens)
                while not warm.done:
                    engine.tick()
            # burst warmup: distinct cold prompts (lead token 2+ so they
            # never prefix-hit the measured [1]-led set) at every
            # power-of-two wave size the engine's batched admission can
            # decompose a wave into — measurement then never compiles
            size = min(serve_slots, len(prompts))
            lead = 2
            while size >= 2:
                warm_burst = [
                    [lead] + [(11 * (lead * 31 + i + j)) % (config.vocab_size - 3) + 3
                              for j in range(len(prompts[0]) - 1)]
                    for i in range(size)
                ]
                burst_reqs = [
                    engine.submit(ids, max_new_tokens=4) for ids in warm_burst
                ]
                while not all(r.done for r in burst_reqs):
                    engine.tick()
                size //= 2
                lead += 1
            # drain warmup's lookahead chunk: its retirement waste and its
            # warmup-boundary window must not leak into the measured deltas
            engine.tick()
            waves_before = engine.batched_waves
            hits_before = engine.prefix_hits
            stats_before = engine.stats()
            # the measured window: loadgen drives the burst (time_scale=0 —
            # every arrival immediate, exactly the old submit-all loop) and
            # brackets it with registry snapshots; tok/s comes from the
            # token-counter delta over the captured_at window
            schedule = schedule_from_prompts(scenario, prompts, new_tokens)
            result = run_schedule(
                schedule, EngineTarget(engine), scenario=scenario, time_scale=0.0,
            )
            loadgen_results.append(result)
            row = scenario_row(result)
            if record_counters:
                # evidence the batched-admission path carried the MEASURED
                # window (deltas, not engine-lifetime totals — warmup hits
                # prompts[0]'s prefix by construction), and only from the
                # headline bf16 run so a failed run can't be papered over
                # by a later variant's counters
                record["serve_batched_waves"] = engine.batched_waves - waves_before
                record["serve_prefix_hits"] = engine.prefix_hits - hits_before
                # pipeline evidence from the same run: how much of the decode
                # window the host overlapped, what it blocked for, and the
                # decode the one-chunk retirement lag threw away — deltas
                # over the measured window, like the wave/hit counters above
                # (warmup's retirement waste and cold-compile windows must
                # not pollute the measured numbers)
                stats = engine.stats()
                stall = stats["host_stall_s"] - stats_before["host_stall_s"]
                window = stats["chunk_window_s"] - stats_before["chunk_window_s"]
                record["serve_overlap"] = stats["overlap"]
                record["serve_overlap_ratio"] = (
                    round(max(0.0, min(1.0, 1.0 - stall / window)), 4) if window > 0 else 0.0
                )
                record["serve_host_stall_s"] = round(stall, 6)
                record["serve_wasted_decode_tokens"] = (
                    stats["wasted_decode_tokens"] - stats_before["wasted_decode_tokens"]
                )
            if record_counters:
                # device-time capture AFTER the measured window (a capture
                # fences every dispatch, which would perturb the headline
                # tok/s): a short driven burst under an open capture yields
                # the per-phase step clock + compile/MFU summary the record
                # embeds as "device_profile" — perf_delta diffs it, and
                # rounds without it stay comparable
                try:
                    engine.profiler.start_capture()
                    profile_reqs = [
                        engine.submit(p, max_new_tokens=min(8, new_tokens))
                        for p in prompts[: min(4, len(prompts))]
                    ]
                    while not all(r.done for r in profile_reqs):
                        engine.tick()
                    engine.tick()  # retire the overlap lookahead chunk
                    capture = engine.profiler.stop_capture()
                    if capture:
                        record["device_profile"] = capture["summary"]
                except Exception as e:  # noqa: BLE001 — profiling is evidence, not the benchmark
                    print(f"# bench: device-profile capture failed: {e}", flush=True)
            if obs_key:
                # full metrics-registry snapshot (TTFT / queue-wait /
                # prefill / decode-step histograms over the warmup+measured
                # window) so BENCH_*.json carries distributions, not just
                # the headline mean
                engine.stats()  # refresh point-in-time gauges
                record[obs_key] = engine.registry.snapshot()
            return row
        finally:
            del engine

    # separate guards: an int8 failure must not mark the bf16 number failed
    try:
        record["serve_tok_s"] = round(
            run_serve(kv_quant=False, record_counters=True, obs_key="serve_obs")["tok_s"], 1
        )
        record["serve_requests"] = n_req
        # roofline approximation: with the queue longer than the slot count
        # the slots stay full, so each decode step streams the weights once
        # for `occupied` tokens plus that many caches at the mean context;
        # prefill ticks are inside the elapsed time → lower bound
        occupied = min(n_req, serve_slots)
        serve_bpt = param_bytes / occupied + _kv_bytes_per_slot(config, 2) * (
            serve_prompt_len + req_new / 2
        )
        serve_gbs = record["serve_tok_s"] * serve_bpt / 1e9
        record["serve_hbm_gbs"] = round(serve_gbs, 1)
        record["serve_hbm_pct_peak"] = round(100.0 * serve_gbs / V5E_HBM_GBS, 1)
        print(
            f"# bench: serve {record['serve_tok_s']} tok/s ({n_req} reqs, "
            f"~{record['serve_hbm_pct_peak']}% HBM peak)",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["serve_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins
    try:
        # int8-cache engine: same load, half the KV HBM traffic per step
        record["serve_int8_tok_s"] = round(
            run_serve(kv_quant=True, obs_key="serve_int8_obs", scenario="serve_int8")["tok_s"], 1
        )
        print(f"# bench: serve int8 {record['serve_int8_tok_s']} tok/s", flush=True)
    except Exception as e:  # noqa: BLE001
        record["serve_int8_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve int8 section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins
    try:
        # speculative on/off over the loadgen DSL's spec_friendly scenario
        # (repetitive/templated completions — the favorable regime:
        # continuations settle into loops, n-gram drafts land, and each
        # fused propose+verify dispatch emits several tokens). BOTH legs run
        # the same schedule through the registry-windowed runner, so the
        # record carries the spec-on/off tok/s + TPOT delta and the accept
        # ratio as SLO-report evidence, not stopwatch numbers. Speculation
        # now rides the overlap pipeline and (in the sharded section's mesh
        # runs) the multi-chip path — docs/architecture.md "Speculative
        # decoding".
        from prime_tpu.loadgen.report import spec_comparison_record
        from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule

        spec_schedule = build_schedule(
            SCENARIOS["spec_friendly"](0), vocab=config.vocab_size
        )
        spec_prompts = [list(r.prompt_ids) for r in spec_schedule]
        spec_new = max(r.max_new_tokens for r in spec_schedule)
        off_row = run_serve(
            prompts=spec_prompts, max_new=spec_new, scenario="serve_spec_off",
        )
        on_row = run_serve(
            speculative=True, prompts=spec_prompts, max_new=spec_new,
            obs_key="serve_spec_obs", scenario="serve_spec",
        )
        record.update(spec_comparison_record(off_row, on_row, digits=1))
        print(
            f"# bench: serve speculative {record['serve_spec_tok_s']} tok/s "
            f"(spec off {record['serve_spec_off_tok_s']}, accept ratio "
            f"{record.get('serve_spec_accept_ratio')})",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["serve_spec_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve speculative section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve: shared-prefix burst (radix prefix-KV cache) -----------------
    # the multi-tenant prompt shape the block cache targets: every request
    # opens with the same system preamble and diverges after it. Reports the
    # prefix-hit ratio over the measured admissions (partial hits count),
    # mean admit (prefill) latency, and one assemble dispatch per hit.
    # built OUTSIDE the try: the host-spill section below reuses these
    # prompts and must not inherit a NameError from an unrelated failure here
    pre_len = 16 if SMOKE else 64
    preamble = [1] + [(5 * j) % (config.vocab_size - 3) + 3 for j in range(pre_len - 1)]
    burst_prompts = [
        preamble
        + [
            (13 * (i * 7 + j)) % (config.vocab_size - 3) + 3
            for j in range(serve_prompt_len - pre_len)
        ]
        for i in range(n_req)
    ]
    try:
        from prime_tpu.serve.engine import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine(
            params, config, pad_id=0, max_slots=serve_slots,
            capacity=SERVE_CAPACITY, chunk=SERVE_CHUNK, prefix_cache_mb=256,
            mesh_config="",  # pin single-chip: an ambient PRIME_SERVE_MESH
            # must not shard the single-chip trajectory sections
        )
        try:
            # warm twice: the first pass compiles the cold plan and stores
            # the preamble blocks; the second compiles the suffix-chunk and
            # assemble shapes the measured burst admissions will hit
            for _ in range(2):
                warm = engine.submit(list(burst_prompts[0]), max_new_tokens=req_new)
                while not warm.done:
                    engine.tick()
            engine.tick()  # drain the lookahead chunk
            before = engine.stats()
            prefill_before = (
                engine.registry.get("serve_prefill_seconds").series_snapshot()
                or {"count": 0, "sum": 0.0}
            )
            # measured burst through loadgen (registry-windowed tok/s)
            burst_schedule = schedule_from_prompts(
                "serve_prefixburst", [list(ids) for ids in burst_prompts], req_new
            )
            burst_result = run_schedule(
                burst_schedule, EngineTarget(engine),
                scenario="serve_prefixburst", time_scale=0.0,
            )
            loadgen_results.append(burst_result)
            burst_row = scenario_row(burst_result)
            after = engine.stats()
            prefill_after = engine.registry.get("serve_prefill_seconds").series_snapshot()
            hits = after["prefix_hits"] - before["prefix_hits"]
            admitted = after["requests_admitted"] - before["requests_admitted"]
            d_count = prefill_after["count"] - prefill_before["count"]
            d_sum = prefill_after["sum"] - prefill_before["sum"]
            record["serve_prefixburst_tok_s"] = burst_row["tok_s"]
            record["serve_prefixburst_hit_ratio"] = (
                round(hits / admitted, 3) if admitted else 0.0
            )
            record["serve_prefixburst_hit_tokens"] = pre_len
            record["serve_prefixburst_admit_ms_mean"] = (
                round(d_sum / d_count * 1e3, 2) if d_count else 0.0
            )
            record["serve_prefixburst_assembles"] = (
                after["prefix_assembles"] - before["prefix_assembles"]
            )
            record["serve_prefixburst_cache_bytes"] = after["prefix_cache_bytes"]
            # per-tier hit tokens (serve_prefix_hit_tokens{tier=...}): the
            # 256 MiB device budget never pressures this burst, so host
            # stays 0 here — the spill-tier section below applies pressure
            hit_hist = engine.registry.get("serve_prefix_hit_tokens")
            for tier in ("device", "host"):
                snap = hit_hist.series_snapshot(tier=tier) or {"sum": 0.0}
                record[f"serve_prefixburst_hit_tokens_{tier}"] = int(snap["sum"])
            record["serve_prefixburst_spills"] = after["prefix_spills"]
            record["serve_prefixburst_reuploads"] = after["prefix_reuploads"]
            engine.stats()  # refresh gauges for the snapshot
            record["serve_prefixburst_obs"] = engine.registry.snapshot()
            print(
                f"# bench: serve shared-prefix burst "
                f"{record['serve_prefixburst_tok_s']} tok/s, hit ratio "
                f"{record['serve_prefixburst_hit_ratio']}, admit "
                f"{record['serve_prefixburst_admit_ms_mean']} ms mean, "
                f"{record['serve_prefixburst_assembles']} assembles",
                flush=True,
            )
        finally:
            del engine
    except Exception as e:  # noqa: BLE001
        record["serve_prefixburst_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve shared-prefix section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve: host spill tier under device-budget pressure ----------------
    # the two-tier prefix cache's reason to exist: a device budget too small
    # for even one prompt's KV (1 KiB here — deliberate, deterministic
    # pressure) forces every stored segment to demote to the host tier, so
    # each later shared-preamble admission hits HOST-resident blocks and
    # pays a re-upload instead of a recompute. Proves hit_tokens{tier=host}
    # > 0 and the spill/re-upload counters move (ROADMAP Open item 3).
    try:
        from prime_tpu.serve.engine import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine(
            params, config, pad_id=0, max_slots=serve_slots,
            capacity=SERVE_CAPACITY, chunk=SERVE_CHUNK,
            prefix_cache_mb=1 / 1024, prefix_cache_host_mb=64,
            mesh_config="",  # pin single-chip (see the prefix section above)
        )
        try:
            for ids in burst_prompts[:3]:
                req = engine.submit(list(ids), max_new_tokens=req_new)
                while not req.done:
                    engine.tick()
            engine.tick()  # drain the lookahead chunk
            tier_stats = engine.stats()
            host_snap = engine.registry.get("serve_prefix_hit_tokens").series_snapshot(
                tier="host"
            ) or {"count": 0, "sum": 0.0}
            record["serve_prefixhost_hit_tokens"] = int(host_snap["sum"])
            record["serve_prefixhost_hits"] = int(host_snap["count"])
            record["serve_prefixhost_spills"] = tier_stats["prefix_spills"]
            record["serve_prefixhost_reuploads"] = tier_stats["prefix_reuploads"]
            record["serve_prefixhost_host_bytes"] = tier_stats["prefix_cache_host_bytes"]
            record["serve_prefixhost_obs"] = engine.registry.snapshot()
            print(
                f"# bench: serve host spill tier "
                f"{record['serve_prefixhost_hit_tokens']} host-tier hit tokens "
                f"over {record['serve_prefixhost_hits']} hits, "
                f"{record['serve_prefixhost_spills']} spills, "
                f"{record['serve_prefixhost_reuploads']} re-uploads, "
                f"{record['serve_prefixhost_host_bytes']} host bytes",
                flush=True,
            )
        finally:
            del engine
    except Exception as e:  # noqa: BLE001
        record["serve_prefixhost_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve host spill tier section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve fleet: 2-replica router, shared-prefix burst -----------------
    # the multi-replica control plane (docs/architecture.md "Serve fleet"):
    # two in-process engines behind a FleetRouter, driven over real HTTP with
    # the same shared-preamble burst as the prefix section. Reports aggregate
    # tok/s and the affinity hit ratio — the fraction of keyed requests the
    # consistent-hash scheduler landed on their prefix-cache-warm replica.
    try:
        import httpx

        from prime_tpu.loadgen import HTTPTarget, NumericTokenizer
        from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend
        from prime_tpu.serve.fleet import serve_fleet
        from prime_tpu.serve.server import InferenceServer

        fleet_slots = max(2, serve_slots // 2)
        # construct INSIDE the guarded block: a failed second server or
        # router must not leak running engine threads (and their KV device
        # allocations) into the later bench sections
        engines: list = []
        servers: list = []
        router = None
        try:
            for _ in range(2):
                engine = ContinuousBatchingEngine(
                    params, config, pad_id=0, max_slots=fleet_slots,
                    capacity=SERVE_CAPACITY, chunk=SERVE_CHUNK, prefix_cache_mb=256,
                    mesh_config="",  # pin single-chip fleet replicas
                )
                engine.start()
                engines.append(engine)
                servers.append(
                    InferenceServer(
                        "bench-fleet", EngineBackend(engine, NumericTokenizer()), port=0
                    ).start()
                )
            router = serve_fleet(
                [srv.url for srv in servers], poll_interval=0.2, model_id="bench-fleet",
            )
            pre_len = 16 if SMOKE else 64
            fleet_prompts = [
                [(5 * j) % (config.vocab_size - 3) + 3 for j in range(pre_len)]
                + [
                    (13 * (i * 7 + j)) % (config.vocab_size - 3) + 3
                    for j in range(serve_prompt_len - pre_len)
                ]
                for i in range(n_req)
            ]
            # the measured burst goes over real HTTP through the router; the
            # report scrapes BOTH replicas' engine registries plus the
            # router's, so fleet tok/s aggregates server-side token counters
            target = HTTPTarget(
                router.url,
                scrape_urls={
                    "router": router.url,
                    **{f"replica{i}": srv.url for i, srv in enumerate(servers)},
                },
                timeout_s=240.0,
            )

            # warm each replica directly (compile prefill/decode/assemble off
            # the measured clock), then let the router's poller observe them
            warm_body = {
                "messages": [{"role": "user",
                              "content": " ".join(str(t) for t in fleet_prompts[0])}],
                "max_tokens": req_new, "temperature": 0.0,
            }
            for srv in servers:
                for _ in range(2):
                    httpx.post(
                        f"{srv.url}/v1/chat/completions", json=warm_body, timeout=240.0,
                    ).raise_for_status()
            time.sleep(0.5)
            fleet_schedule = schedule_from_prompts(
                "serve_fleet", fleet_prompts, req_new
            )
            fleet_result = run_schedule(
                fleet_schedule, target, scenario="serve_fleet", time_scale=0.0,
                max_workers=8,
            )
            loadgen_results.append(fleet_result)
            fleet_row = scenario_row(fleet_result)
            stats = router.stats()
            record["serve_fleet_tok_s"] = fleet_row["tok_s"]
            # the old fleet_post raise_for_status aborted the section on any
            # failed request; loadgen folds failures into outcomes instead —
            # surface them at record level so a half-dead fleet's survivor
            # throughput can never read as a healthy number
            if fleet_result.outcomes.get("failed"):
                record["serve_fleet_error"] = (
                    f"{fleet_result.outcomes['failed']} of {len(fleet_schedule)} "
                    "requests failed; tok_s covers survivors only"
                )
            record["serve_fleet_affinity_ratio"] = stats["affinity_hit_ratio"]
            record["serve_fleet_reroutes"] = stats["reroutes"]
            # placement split: requests landed by advertised cached prefix
            # (digest-guided saturation fallback) vs by the consistent hash
            # (affinity target or blind least-loaded). Both terms are
            # per-PICK counters — requests_by_replica counts per forward
            # attempt, which double-counts failover retries
            record["serve_fleet_cache_routed"] = stats["cache_routed"]
            record["serve_fleet_hash_routed"] = (
                stats["affinity_requests"] - stats["cache_routed"]
            )
            record["serve_fleet_requests_by_replica"] = {
                rid: sum(outcomes.values())
                for rid, outcomes in stats["requests_by_replica"].items()
            }
            record["serve_fleet_obs"] = router.registry.snapshot()
            print(
                f"# bench: serve fleet (2 replicas) {record['serve_fleet_tok_s']} "
                f"tok/s aggregate, affinity hit ratio "
                f"{record['serve_fleet_affinity_ratio']}, per-replica "
                f"{record['serve_fleet_requests_by_replica']}",
                flush=True,
            )
        finally:
            if router is not None:
                router.stop()
            for srv in servers:
                srv.stop()  # also shuts down the backing engine
            for engine in engines[len(servers):]:
                engine.shutdown()  # engine started but its server never did
    except Exception as e:  # noqa: BLE001
        record["serve_fleet_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve fleet section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve: disaggregated prefill/decode (phase-split fleet) ------------
    # The long-prompt-heavy `disagg` scenario against the SAME two-engine
    # device budget twice: colocated (two any-role replicas) vs phase-split
    # (1 prefill + 1 decode replica, KV migrated over GET/PUT /admin/kv in
    # the prefix-cache wire format). Both runs are registry-windowed through
    # loadgen; the record carries both tok/s, both TTFT p95s, and the
    # migration outcome/byte evidence — docs/architecture.md "Disaggregated
    # serving". Engine warmup is off here (the direct + router warm passes
    # inside the comparison cover the shapes in play; AOT warmup on a remote
    # TPU costs minutes per engine).
    try:
        from prime_tpu.loadgen.scenario import loadgen_seed_default
        from prime_tpu.loadgen.smoke import disagg_comparison

        # smoke mode swaps the tiny bench model for debug-128m: at tiny-test
        # scale the migration's fixed per-request cost dwarfs the prefill it
        # offloads, so the comparison would measure the harness, not the
        # architecture (same rule the loadgen smoke's disagg section follows)
        # — a real-model bench round keeps the bench checkpoint
        if SMOKE:
            from prime_tpu.models import get_config as _get_config

            disagg_config = _get_config("debug-128m")
            disagg_params = init_params(
                jax.random.PRNGKey(0), disagg_config, dtype=jnp.float32
            )
        else:
            disagg_config, disagg_params = config, params
        disagg_record, disagg_rows = disagg_comparison(
            disagg_config, lambda i: disagg_params, seed=loadgen_seed_default(),
            model_id="bench-disagg", max_slots=max(2, serve_slots // 2),
            capacity=SERVE_CAPACITY, chunk=SERVE_CHUNK, warmup=False,
            log=lambda msg: print(f"# bench: {msg.lstrip('# ')}", flush=True),
        )
        record.update(disagg_record)
        # the comparison builds its own HTTP fleets, so its RunResults are
        # already folded into the rows it returns — append them to the SLO
        # report the same way the in-process sections' results are
        loadgen_rows_extra.extend(disagg_rows)
    except Exception as e:  # noqa: BLE001
        record["serve_disagg_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve disagg section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- sharded replica serve section (the MULTICHIP serving number) -------
    # ONE engine spanning every visible device (docs/architecture.md "Sharded
    # replica"): the engine builds the (dp, fsdp, tp) mesh from a declarative
    # spec, places params + paged KV as NamedSharding arrays, and is measured
    # through the same loadgen path as the single-chip sections — so the
    # tok/s is registry-windowed and the run lands in the SLO report as its
    # own scenario. On the virtual-device CPU smoke this is the committed
    # MULTICHIP trajectory's serving number; on a real slice it is the
    # per-topology throughput PAPERS' Gemma-on-TPU serving table reports.
    try:
        import math as _math

        n_dev = jax.device_count()
        if n_dev > 1:
            # tp over the kv heads it must divide; the rest of the slice
            # becomes the fsdp data axis (batch = slots shard over it)
            tp = _math.gcd(n_dev, config.n_kv_heads)
            mesh_spec = f"dp=1,fsdp={n_dev // tp},tp={tp}"
            record["serve_mesh"] = mesh_spec
            record["serve_mesh_devices"] = n_dev
            record["serve_sharded_tok_s"] = round(
                run_serve(
                    obs_key="serve_sharded_obs", scenario="serve_sharded",
                    mesh_config=mesh_spec,
                )["tok_s"],
                1,
            )
            print(
                f"# bench: serve sharded {record['serve_sharded_tok_s']} tok/s "
                f"(one replica over mesh {mesh_spec}, {n_dev} devices)",
                flush=True,
            )
        else:
            print("# bench: serve sharded section skipped (single device)", flush=True)
    except Exception as e:  # noqa: BLE001
        record["serve_sharded_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve sharded section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- loadgen SLO report over every serve section ------------------------
    # the schema-2 artifact: one row per driven scenario (serve, int8, spec,
    # prefixburst, fleet) with registry-derived tok/s, TTFT/TPOT p50/p95,
    # overlap and hit ratios — what scripts/perf_delta.py flattens into the
    # per-PR trajectory and scripts/serve_profile.py --slo merges with traces
    try:
        if loadgen_results or loadgen_rows_extra:
            # loadgen_rows_extra alone still produces a report: the disagg
            # comparison builds its own fleets, so its rows must survive
            # even a round where every in-process serve section failed
            record["loadgen"] = build_report(
                loadgen_results,
                meta={"backend": record.get("backend", "unknown")},
                device_profile=record.get("device_profile"),
            )
            # disagg-comparison rows ride along without joining the headline
            # (their fleets are separate stacks; the headline stays the
            # driven-engine sections' aggregate, exactly as before)
            record["loadgen"]["scenarios"].extend(loadgen_rows_extra)
            headline = record["loadgen"]["headline"]
            print(
                f"# bench: loadgen SLO report — {len(loadgen_results)} scenarios, "
                f"aggregate {headline['tok_s']} tok/s over "
                f"{headline['requests']} requests",
                flush=True,
            )
    except Exception as e:  # noqa: BLE001
        record["loadgen_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: loadgen report assembly failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve fleet: cache-aware vs blind routing (deterministic sim) ------
    # pure balancer-level A/B — no sockets, no engines, no clocks — of the
    # tentpole routing upgrade: the same saturating shared-preamble workload
    # placed twice, once with replicas advertising their hot-prefix digests
    # (saturation fallback diverts to the longest advertised cached prefix)
    # and once blind (pre-digest least-loaded). Scores each placement by how
    # many leading blocks of the request the chosen replica's ACTUAL cache
    # held; the digest run must win the hit-token ratio.
    try:
        from collections import Counter as _Counter
        from collections import deque as _deque

        from prime_tpu.serve.digest import (
            HotPrefixDigest,
            longest_match_blocks,
            prefix_hashes,
        )
        from prime_tpu.serve.fleet.balancer import PrefixAffinityBalancer
        from prime_tpu.serve.fleet.membership import FleetMembership

        # 12 tenant groups over 4 replicas in deterministic-but-irregular
        # (LCG) arrival order: each preamble spans 3 digest blocks, each tail
        # is request-unique. Replica retention is the REAL bounded
        # HotPrefixDigest LRU (20 entries ~ a few groups' chains), so blind
        # scattering churns a replica's hot set while cache-aware placement
        # keeps re-landing a group where its preamble still survives.
        sim_prompts, lcg = [], 1
        for i in range(120):
            lcg = (lcg * 1103515245 + 12345) % (1 << 31)
            preamble = (f"tenant {lcg % 12} system preamble block " * 12)[:192]
            sim_prompts.append(preamble + f" user question {i} " * 8)

        def _route_sim(cache_aware: bool) -> tuple[float, int]:
            membership = FleetMembership(
                [f"http://10.0.0.{i}:9" for i in (1, 2, 3, 4)]
            )
            # saturation_depth=1: a backlog of one is tolerable, two diverts —
            # leaves multiple unsaturated candidates at UNEQUAL loads, the
            # regime where digest depth and least-loaded genuinely disagree
            balancer = PrefixAffinityBalancer(membership, saturation_depth=1)
            caches = {
                rid: HotPrefixDigest(max_entries=20) for rid in membership.replicas
            }
            recent: _deque = _deque(maxlen=6)  # each request occupies its
            # replica for the next 6 placements — emergent saturation
            hit_blocks = total_blocks = cache_routed = 0
            for prompt in sim_prompts:
                depths = _Counter(recent)
                for rid, replica in membership.replicas.items():
                    replica.queue_depth = depths.get(rid, 0)
                pick = balancer.pick(prompt)
                chain = prefix_hashes(prompt)
                hit_blocks += longest_match_blocks(
                    chain, set(caches[pick.replica.id].hashes())
                )
                total_blocks += len(chain)
                cache_routed += bool(pick.cache_routed)
                caches[pick.replica.id].observe(prompt)
                if cache_aware:
                    pick.replica.digest = frozenset(caches[pick.replica.id].hashes())
                recent.append(pick.replica.id)
            return hit_blocks / total_blocks, cache_routed

        aware_ratio, aware_cache_routed = _route_sim(cache_aware=True)
        blind_ratio, _ = _route_sim(cache_aware=False)
        record["serve_fleet_routesim_hit_ratio_cache_aware"] = round(aware_ratio, 4)
        record["serve_fleet_routesim_hit_ratio_blind"] = round(blind_ratio, 4)
        record["serve_fleet_routesim_cache_routed"] = aware_cache_routed
        record["serve_fleet_routesim_requests"] = len(sim_prompts)
        print(
            f"# bench: fleet routing sim prefix-hit-token ratio "
            f"{record['serve_fleet_routesim_hit_ratio_cache_aware']} cache-aware vs "
            f"{record['serve_fleet_routesim_hit_ratio_blind']} blind "
            f"({aware_cache_routed}/{len(sim_prompts)} cache-routed)",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["serve_fleet_routesim_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: fleet routing sim failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- quant: int8 weights / int8 KV --------------------------------------
    try:
        from prime_tpu.models.quantize import quantize_params_int8

        qparams = quantize_params_int8(params)

        def run_q(kv_quant=False):
            # auto dispatch: int8 caches ride the flash kernel at long
            # context now (round 4); short-context headline stays XLA
            result = generate(
                qparams, prompts, lengths, config, jax.random.PRNGKey(2),
                max_new_tokens=NEW_TOKENS, temperature=0.0,
                **({"kv_quant": True} if kv_quant else {}),
            )
            float(jnp.sum(result.tokens))

        q_s = time_fn(run_q)
        qkv_s = time_fn(lambda: run_q(kv_quant=True))
        record["int8_weights_tok_s"] = round(BATCH * NEW_TOKENS / q_s, 1)
        record["int8_weights_kv_tok_s"] = round(BATCH * NEW_TOKENS / qkv_s, 1)
        # roofline over the full gen time (prefill included → lower bound);
        # int8 caches move 1 byte/elem plus a 4-byte fp32 scale per slot-head
        qparam_bytes = _tree_bytes(qparams)
        record["int8_param_gb"] = round(qparam_bytes / 1e9, 3)
        ctx_avg = PROMPT_LEN + NEW_TOKENS / 2
        record.update(
            _decode_roofline(
                qparam_bytes, config, BATCH, ctx_avg, NEW_TOKENS, q_s,
                prefix="int8_",
            )
        )
        record.update(
            _decode_roofline(
                qparam_bytes, config, BATCH, ctx_avg, NEW_TOKENS, qkv_s,
                kv_bytes=1 + 4 / config.head_dim, prefix="int8_kv_",
            )
        )
        print(
            f"# bench: int8 weights {record['int8_weights_tok_s']} tok/s "
            f"({record['int8_hbm_pct_peak']}% HBM peak)",
            flush=True,
        )
        # int4 weights (W4A16 group-wise): half the int8 weight bytes again —
        # at decode the weights dominate HBM traffic, so this is the deepest
        # single-chip bandwidth lever in the stack. Nested guard: an int4
        # failure must not erase the int8 numbers already recorded above.
        try:
            from prime_tpu.models.quantize import quantize_params_int4

            q4params = quantize_params_int4(params)

            def run_q4(kv_quant=False):
                result = generate(
                    q4params, prompts, lengths, config, jax.random.PRNGKey(2),
                    max_new_tokens=NEW_TOKENS, temperature=0.0,
                    **({"kv_quant": True} if kv_quant else {}),
                )
                float(jnp.sum(result.tokens))

            q4_s = time_fn(run_q4)
            q4kv_s = time_fn(lambda: run_q4(kv_quant=True))
            record["int4_weights_tok_s"] = round(BATCH * NEW_TOKENS / q4_s, 1)
            record["int4_weights_kv_tok_s"] = round(BATCH * NEW_TOKENS / q4kv_s, 1)
            q4param_bytes = _tree_bytes(q4params)
            record["int4_param_gb"] = round(q4param_bytes / 1e9, 3)
            record.update(
                _decode_roofline(
                    q4param_bytes, config, BATCH, ctx_avg, NEW_TOKENS, q4_s,
                    prefix="int4_",
                )
            )
            record.update(
                _decode_roofline(
                    q4param_bytes, config, BATCH, ctx_avg, NEW_TOKENS, q4kv_s,
                    kv_bytes=1 + 4 / config.head_dim, prefix="int4_kv_",
                )
            )
            print(
                f"# bench: int4 weights {record['int4_weights_tok_s']} tok/s "
                f"({record['int4_hbm_pct_peak']}% HBM peak)",
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            record["int4_error"] = f"{type(e).__name__}: {e}"[:200]
            print(f"# bench: int4 subsection failed: {e}", flush=True)
    except Exception as e:  # noqa: BLE001
        record["quant_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: quant section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- longctx: flash-decode pallas kernel vs XLA at C=4096 ---------------
    # The regime the kernel exists for (short context dispatches to XLA via
    # PRIME_TPU_FLASH_DECODE_MIN_C). VERDICT r2 #5: prove it or retire it.
    try:
        # prompt+new must be a multiple of the flash-decode kernel's 128-slot
        # block: generate() sizes the cache to exactly prompt+new, and the
        # forced impl="pallas" path skips the auto-dispatch alignment check —
        # an unaligned capacity makes the kernel's last block misread the tail
        lc_batch, lc_prompt, lc_new = (2, 120, 8) if SMOKE else (4, 4032, 64)
        lc_prompts = jax.random.randint(
            jax.random.PRNGKey(3), (lc_batch, lc_prompt), 1, config.vocab_size
        )

        def run_lc(impl):
            result = generate(
                params,
                lc_prompts,
                jnp.full((lc_batch,), lc_prompt, dtype=jnp.int32),
                config,
                jax.random.PRNGKey(2),
                max_new_tokens=lc_new,
                temperature=0.0,
                attn_impl=impl,
            )
            float(jnp.sum(result.tokens))

        xla_s = time_fn(lambda: run_lc("xla"), iterations=2)
        pallas_s = time_fn(lambda: run_lc("pallas"), iterations=2)
        record["longctx_xla_tok_s"] = round(lc_batch * lc_new / xla_s, 1)
        record["longctx_pallas_tok_s"] = round(lc_batch * lc_new / pallas_s, 1)
        record["longctx_pallas_speedup"] = round(xla_s / pallas_s, 3)
        print(
            f"# bench: longctx C={lc_prompt + lc_new} pallas {record['longctx_pallas_tok_s']} vs "
            f"xla {record['longctx_xla_tok_s']} tok/s",
            flush=True,
        )
        # int8-KV at long context: the round-4 kernel streams half the cache
        # bytes with scales folded — the regime the variant exists for
        def run_lc_q(impl):
            result = generate(
                params,
                lc_prompts,
                jnp.full((lc_batch,), lc_prompt, dtype=jnp.int32),
                config,
                jax.random.PRNGKey(2),
                max_new_tokens=lc_new,
                temperature=0.0,
                attn_impl=impl,
                kv_quant=True,
            )
            float(jnp.sum(result.tokens))

        q_xla_s = time_fn(lambda: run_lc_q("xla"), iterations=2)
        q_pallas_s = time_fn(lambda: run_lc_q("pallas"), iterations=2)
        record["longctx_int8kv_xla_tok_s"] = round(lc_batch * lc_new / q_xla_s, 1)
        record["longctx_int8kv_pallas_tok_s"] = round(lc_batch * lc_new / q_pallas_s, 1)
        record["longctx_int8kv_pallas_speedup"] = round(q_xla_s / q_pallas_s, 3)
        print(
            f"# bench: longctx int8-KV pallas {record['longctx_int8kv_pallas_tok_s']} vs "
            f"xla {record['longctx_int8kv_xla_tok_s']} tok/s",
            flush=True,
        )
        # rooflines LAST and exception-isolated: attribute decode-only time
        # by timing the long prefill once — at C≈4k the prefill dominates the
        # gen call, so the raw gen time would understate the decode kernel's
        # achieved bandwidth severalfold. A failure here (e.g. OOM from the
        # extra prefill cache) must not lose the tok/s comparisons above.
        try:
            from prime_tpu.models.llama import forward as _fwd, init_cache as _ic

            lc_cache = _ic(config, lc_batch, lc_prompt + lc_new)
            # args not closures — see the headline prefill_fn note
            lc_pre_fn = jax.jit(
                lambda p, c: _fwd(p, lc_prompts, config, cache=c)[0]
            )
            lc_pre_s = time_fn(
                lambda: float(jnp.sum(lc_pre_fn(params, lc_cache))), iterations=2
            )
            record["longctx_prefill_ms"] = round(lc_pre_s * 1e3, 1)
            # same noise guard as the headline: both operands are large and noisy
            if pallas_s - lc_pre_s > 0.2 * pallas_s:
                record.update(
                    _decode_roofline(
                        param_bytes, config, lc_batch, lc_prompt + lc_new / 2,
                        lc_new, pallas_s - lc_pre_s, prefix="longctx_",
                    )
                )
            if q_pallas_s - lc_pre_s > 0.2 * q_pallas_s:
                record.update(
                    _decode_roofline(
                        param_bytes, config, lc_batch, lc_prompt + lc_new / 2,
                        lc_new, q_pallas_s - lc_pre_s,
                        kv_bytes=1 + 4 / config.head_dim,
                        prefix="longctx_int8kv_",
                    )
                )
        except Exception as e:  # noqa: BLE001
            record["longctx_roofline_error"] = f"{type(e).__name__}: {e}"[:200]
    except Exception as e:  # noqa: BLE001
        record["longctx_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: longctx section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- mlactx: MLA latent-cache decode at long context --------------------
    # The round-5 architecture lever: a 1.16B MLA model (DeepSeek-V2 dims,
    # rank-512 latent) decodes at the same long context as the llama longctx
    # section. Per token per layer the latent streams 577*2 ≈ 1.1 KiB vs the
    # 8 KiB its own 16x(128+64) heads would need as full K/V (7x) and the
    # 2 KiB of llama3.2-1b's already-GQA-compressed 8x64 cache (1.7x) —
    # MLA reaches GQA-class cache size WITHOUT sharing heads, at 16 full-
    # width query heads.
    try:
        mla_cfg = get_config("tiny-mla" if SMOKE else "mla-1b")
        mla_params = init_params(jax.random.PRNGKey(30), mla_cfg, dtype=jnp.bfloat16)
        mb, mp, mn = (2, 120, 8) if SMOKE else (4, 4032, 64)
        mla_prompts = jax.random.randint(
            jax.random.PRNGKey(31), (mb, mp), 1, mla_cfg.vocab_size
        )

        def run_mla():
            result = generate(
                mla_params, mla_prompts,
                jnp.full((mb,), mp, dtype=jnp.int32), mla_cfg,
                jax.random.PRNGKey(32), max_new_tokens=mn, temperature=0.0,
            )
            float(jnp.sum(result.tokens))

        mla_s = time_fn(run_mla, iterations=2)
        record["mlactx_tok_s"] = round(mb * mn / mla_s, 1)
        mla_param_bytes = _tree_bytes(mla_params)
        record["mlactx_param_gb"] = round(mla_param_bytes / 1e9, 3)
        record["mlactx_cache_gb_per_4k_seq"] = round(
            mla_cfg.n_layers * (mla_cfg.mla_cache_dim + 1) * 2 * 4096 / 1e9, 4
        )
        # attribute the decode phase by timing the 4k prefill alone (same
        # scheme as longctx): at this prompt length the prefill dominates
        # the gen call, and a whole-call roofline would understate the
        # decode bandwidth severalfold. The latent cache streams twice per
        # step (K and V reads share the array) plus the 1-wide dummy.
        mla_slot = mla_cfg.n_layers * (2 * mla_cfg.mla_cache_dim + 1) * 2
        per_step = mla_param_bytes + mb * mla_slot * (mp + mn / 2)

        def emit_mla_roofline(seconds: float) -> None:
            gbs = per_step * mn / seconds / 1e9
            record["mlactx_hbm_gbs"] = round(gbs, 1)
            record["mlactx_hbm_pct_peak"] = round(100.0 * gbs / V5E_HBM_GBS, 1)

        try:
            from prime_tpu.models.llama import forward as _mla_fwd
            from prime_tpu.models.llama import init_cache as _mla_ic

            mla_cache = _mla_ic(mla_cfg, mb, mp + mn)
            mla_pre_fn = jax.jit(
                lambda p, c: _mla_fwd(
                    p, mla_prompts, mla_cfg, cache=c,
                    last_positions=jnp.full((mb,), mp - 1, dtype=jnp.int32),
                )[0]
            )
            mla_pre_s = time_fn(
                lambda: float(jnp.sum(mla_pre_fn(mla_params, mla_cache))),
                iterations=2,
            )
            record["mlactx_prefill_ms"] = round(mla_pre_s * 1e3, 1)
            decode_s = mla_s - mla_pre_s
            if decode_s > 0.2 * mla_s:
                record["mlactx_decode_tok_s"] = round(mb * mn / decode_s, 1)
                emit_mla_roofline(decode_s)
            else:
                # noisy subtraction: keep the whole-call lower bound so the
                # record never loses the mlactx_hbm_* keys
                emit_mla_roofline(mla_s)
        except Exception as e:  # noqa: BLE001
            record["mlactx_roofline_error"] = f"{type(e).__name__}: {e}"[:200]
            emit_mla_roofline(mla_s)  # whole-call lower bound
        print(
            f"# bench: mlactx C={mp + mn} {record['mlactx_tok_s']} tok/s "
            f"(latent cache, ~{record.get('mlactx_hbm_pct_peak', 0)}% HBM peak)",
            flush=True,
        )
        del mla_params
    except Exception as e:  # noqa: BLE001
        record["mlactx_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: mlactx section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- trainstep: full training-step throughput + MFU ---------------------
    # The other half of the framework: one adamw step (fwd + bwd + fp32
    # update) on a 0.5B dense model, remat="dots". MFU uses the standard
    # model-FLOP count (6*N per token + causal attention fwd*3), NOT the
    # rematerialized hardware FLOPs.
    try:
        from prime_tpu.train.trainer import (
            default_optimizer,
            init_train_state,
            make_train_step,
        )

        tr_model = "tiny-test" if SMOKE else "qwen2.5-0.5b"
        tr_cfg = get_config(tr_model)
        tr_b, tr_s = (2, 64) if SMOKE else (4, 1024)
        tr_params = init_params(jax.random.PRNGKey(40), tr_cfg, dtype=jnp.bfloat16)
        tr_opt = default_optimizer()
        holder = {"state": init_train_state(tr_params, tr_opt)}
        step_fn = make_train_step(tr_cfg, tr_opt, remat="dots")
        tr_tokens = jax.random.randint(
            jax.random.PRNGKey(41), (tr_b, tr_s + 1), 1, tr_cfg.vocab_size
        )
        tr_mask = jnp.ones((tr_b, tr_s), dtype=jnp.float32)

        def run_train_step():
            state, metrics = step_fn(
                holder["state"], tr_tokens[:, :-1], tr_tokens[:, 1:], tr_mask
            )
            holder["state"] = state
            float(metrics["loss"])  # host sync

        tr_step_s = time_fn(run_train_step, iterations=3)
        tr_param_count = _tree_bytes(tr_params) / 2  # bf16 storage
        tr_tokens_per_step = tr_b * tr_s
        tr_flops = (
            6.0 * tr_param_count * tr_tokens_per_step
            # causal fwd attention is 2*L*H*S^2*hd (same model as the
            # headline prefill MFU); fwd + 2x bwd = 3x that
            + 6.0 * tr_cfg.n_layers * tr_cfg.n_heads
            * tr_b * tr_s**2 * tr_cfg.head_dim
        )
        record["trainstep_tok_s"] = round(tr_tokens_per_step / tr_step_s, 1)
        record["trainstep_step_ms"] = round(tr_step_s * 1e3, 1)
        record["trainstep_mfu_pct"] = round(
            100.0 * tr_flops / tr_step_s / V5E_BF16_FLOPS, 1
        )
        record["trainstep_model"] = tr_model
        print(
            f"# bench: trainstep {record['trainstep_tok_s']} tok/s "
            f"({record['trainstep_mfu_pct']}% MFU, b{tr_b} s{tr_s})",
            flush=True,
        )
        del tr_params, holder
    except Exception as e:  # noqa: BLE001
        record["trainstep_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: trainstep section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- winctx: sliding-window flash decode at long context ----------------
    # The round-4 kernel variant: a sliding layer's decode step front-skips
    # cache blocks before the window, so it streams ~window slots instead of
    # the whole cache (Gemma2/3, Mistral, GPT-OSS layers). Microbench of the
    # decode step itself at C=4096 / window=1024: pallas-with-skip vs the
    # XLA path that reads everything and masks.
    try:
        from prime_tpu.ops.attention import decode_attention

        wb, wh, wkh, wd, wc, wwin = (
            (2, 4, 2, 64, 256, 128) if SMOKE else (8, 32, 8, 64, 4096, 1024)
        )
        wq = jax.random.normal(jax.random.PRNGKey(7), (wb, wh, 1, wd), dtype=jnp.bfloat16)
        wk = jax.random.normal(jax.random.PRNGKey(8), (wb, wkh, wd, wc), dtype=jnp.bfloat16)
        wv = jax.random.normal(jax.random.PRNGKey(9), (wb, wkh, wd, wc), dtype=jnp.bfloat16)
        wlens = jnp.full((wb,), wc, dtype=jnp.int32)

        # chained in-jit timing (time_op): a single dispatch per wall-clock
        # sample is pure tunnel RTT at this microsecond scale — both sides
        # run a serialized chain of ops and the overhead cancels in the
        # long-minus-short difference
        def win_op(impl):
            return lambda qc, k, v, lens: decode_attention(
                qc, k, v, lens, wd**-0.5, impl=impl, window=wwin,
                sliding=jnp.asarray(True),
            )

        win_ops = (wk, wv, wlens)
        win_xla_s = time_op(win_op("xla"), wq, win_ops)
        win_pallas_s = time_op(win_op("pallas"), wq, win_ops)
        record["winctx_xla_us"] = round(win_xla_s * 1e6, 1)
        record["winctx_pallas_us"] = round(win_pallas_s * 1e6, 1)
        record["winctx_pallas_speedup"] = round(win_xla_s / win_pallas_s, 3)
        # single-op roofline: the band-skip kernel streams ~window KV slots
        # (2 bytes × K and V); the XLA path streams the whole cache
        win_kernel_bytes = wb * wkh * wd * wwin * 2 * 2
        win_gbs = win_kernel_bytes / win_pallas_s / 1e9
        record["winctx_hbm_gbs"] = round(win_gbs, 1)
        record["winctx_hbm_pct_peak"] = round(100.0 * win_gbs / V5E_HBM_GBS, 1)
        print(
            f"# bench: winctx C={wc} win={wwin} pallas {record['winctx_pallas_us']}us "
            f"vs xla {record['winctx_xla_us']}us",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["winctx_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: winctx section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- spdecode: sequence-parallel decode step ----------------------------
    # The long-context decode path a v5e-8+ slice runs (cache slots sharded
    # over sp, two-phase softmax combine — parallel/long_context.py), timed
    # through the IDENTICAL shard_map code on the bench chip's sp=1 mesh.
    # What a single chip can measure is the sp machinery's overhead vs the
    # plain decode step (expect ~1.0x); cross-chip scaling needs a slice the
    # driver doesn't have. Parity at sp=8 is locked by
    # tests/test_parallel.py::test_sp_decode_parity_long_cache.
    try:
        from prime_tpu.ops.attention import decode_attention
        from prime_tpu.parallel.long_context import sp_decode_attention
        from prime_tpu.parallel.mesh import make_mesh

        sp_b, sp_h, sp_kh, sp_d, sp_c = (
            (2, 4, 2, 64, 256) if SMOKE else (8, 32, 8, 64, 4096)
        )
        sp_q = jax.random.normal(jax.random.PRNGKey(4), (sp_b, sp_h, 1, sp_d), dtype=jnp.bfloat16)
        sp_k = jax.random.normal(jax.random.PRNGKey(5), (sp_b, sp_kh, sp_d, sp_c), dtype=jnp.bfloat16)
        sp_v = jax.random.normal(jax.random.PRNGKey(6), (sp_b, sp_kh, sp_d, sp_c), dtype=jnp.bfloat16)
        sp_lens = jnp.full((sp_b,), sp_c, dtype=jnp.int32)
        mesh1 = make_mesh({"sp": 1})
        # chained in-jit timing (time_op) for the same reason as winctx: one
        # dispatch per sample measures tunnel RTT, not the op
        sp_operands = (sp_k, sp_v, sp_lens)
        plain_s = time_op(
            lambda qc, k, v, lens: decode_attention(
                qc, k, v, lens, sp_d**-0.5, impl="xla"
            ),
            sp_q, sp_operands,
        )
        sp_s = time_op(
            lambda qc, k, v, lens: sp_decode_attention(qc, k, v, lens, mesh1),
            sp_q, sp_operands,
        )
        record["spdecode_plain_us"] = round(plain_s * 1e6, 1)
        record["spdecode_sp_us"] = round(sp_s * 1e6, 1)
        record["spdecode_overhead"] = round(sp_s / plain_s, 3)
        # single-op roofline: one decode step streams the full K+V cache
        sp_kernel_bytes = sp_b * sp_kh * sp_d * sp_c * 2 * 2
        sp_gbs = sp_kernel_bytes / sp_s / 1e9
        record["spdecode_hbm_gbs"] = round(sp_gbs, 1)
        record["spdecode_hbm_pct_peak"] = round(100.0 * sp_gbs / V5E_HBM_GBS, 1)
        print(
            f"# bench: spdecode C={sp_c} sp-path {record['spdecode_sp_us']}us vs "
            f"plain {record['spdecode_plain_us']}us",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["spdecode_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: spdecode section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # final, enriched record — last JSON line on stdout wins
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
