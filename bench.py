"""Benchmark: TPU throughput on the north-star paths (BASELINE.md).

Sections (each prints a `# bench:` progress line; ONE final JSON line):
  headline   decode tokens/sec — llama3.2-1b bf16, batch 8, 128+128 greedy
  eval       eval samples/sec THROUGH EvalRunner (tokenize → batch → sharded
             generate → score → results.jsonl) — the BASELINE.json metric
  serve      continuous-batching engine tokens/sec under concurrent load
  quant      int8 weights / int8 KV variants of the headline
  longctx    flash-decode pallas kernel vs XLA at C=4096 (the regime the
             kernel was built for; short-context already dispatches to XLA)

The record is unlosable by construction (last-JSON-line-wins, so each print
below overwrites the one before): a provisional abort line prints BEFORE the
preflight (round 3's driver kill mid-preflight left parsed:null), the
structured abort with diagnosis prints on preflight failure, the headline
prints as soon as measured, and the enriched record prints last. The
preflight itself is bounded at ~7.5 min — each probe longer than the
tunnel's observed ~150 s success latency (rounds 1-2 undercut it and
recorded 0.0), total well under the driver's wall clock (round 3 overshot
it and recorded nothing).

The reference publishes no numbers (BASELINE.json "published": {}), so
vs_baseline is the ratio against PREV_DECODE_TOK_S — this repo's round-1
measured anchor.
"""

import json
import os
import subprocess
import sys
import time

# Round-1 anchor (v5e-1, this repo @ first bench). vs_baseline = value / this.
PREV_DECODE_TOK_S = 1396.6

BATCH = 8
PROMPT_LEN = 128
NEW_TOKENS = 128
MODEL = "llama3.2-1b"

# Observed on the axon tunnel (scripts/tpu_watch.sh, round 3): a trivial
# matmul probe SUCCEEDS but takes ~150 s end-to-end (interpreter + PJRT
# handshake + first compile over the relay). Rounds 1-2 probed with a 120 s
# timeout and recorded the backend as "unresponsive" — the probe budget must
# comfortably exceed the success latency, not undercut it. Round 3's probe
# schedule (5 × 330 s + waits, ~35 min worst case) exceeded the DRIVER's
# budget instead: rc=124 with no JSON printed (BENCH_r03.json parsed:null).
# Both bounds matter: each probe > ~150 s success latency, total ≤ ~8 min.
PROBE_TIMEOUT_S = 210.0
PROBE_WAITS_S = (30.0,)  # between attempts; 2*210+30 = 7.5 min worst case


def _sweep_stray_holders() -> list[str]:
    """Kill leftover TPU-touching helper processes from the round so the
    bench (and the driver's end-of-round snapshot) owns the chip cleanly:
    the reachability watcher (scripts/tpu_watch.sh) and any orphaned probe
    interpreters. Never touches this process or its ancestors."""
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(10):
        try:
            with open(f"/proc/{pid}/stat") as f:
                # comm (field 2) may itself contain spaces/parens — ppid is
                # the 2nd field AFTER the last ')', not split()[3]
                after_comm = f.read().rsplit(")", 1)[1].split()
                pid = int(after_comm[1])
        except (OSError, ValueError, IndexError):
            break
        if pid <= 1:
            break
        ancestors.add(pid)
    killed = []
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,args"], capture_output=True, text=True, timeout=10
        ).stdout
    except Exception:
        return killed
    for line in out.splitlines()[1:]:
        parts = line.strip().split(None, 1)
        if len(parts) != 2:
            continue
        pid_s, cmd = parts
        try:
            pid = int(pid_s)
        except ValueError:
            continue
        if pid == me or pid in ancestors:
            continue
        # exact helper signatures only: the watcher's shell process (bash
        # running the script — NOT an editor/grep whose argv mentions it),
        # probe interpreters (python -c with the probe matmul literal), and
        # a CONCURRENT bench.py (the watcher's opportunistic capture — a
        # SIGKILL to its parent shell orphans the child, which would keep
        # holding the single-client chip through the driver's preflight)
        is_watcher = "bash" in cmd and cmd.rstrip().endswith("tpu_watch.sh")
        is_probe = "python" in cmd and "-c" in cmd and "jnp.ones((256" in cmd
        is_bench = "python" in cmd and cmd.rstrip().endswith("bench.py")
        if is_watcher or is_probe or is_bench:
            try:
                os.kill(pid, 9)
                killed.append(f"{pid}:{cmd[:60]}")
            except OSError:
                pass
    return killed


def _probe_once(timeout_s: float) -> str | None:
    """One accelerator probe in a SUBPROCESS (fresh PJRT client — an
    in-process retry would reuse the same stuck client). None on success."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "x = jnp.ones((256, 256))\n"
        "print(float(jnp.sum(x @ x)))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=timeout_s
        )
    except subprocess.TimeoutExpired:
        return f"backend unresponsive after {timeout_s:.0f}s"
    if proc.returncode != 0:
        return f"probe rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    return None


def _diagnose() -> dict:
    """On preflight failure: enumerate candidate chip-holding processes and
    environment state so the record says WHY, not just 'unresponsive'."""
    # key NAMES only (plus the one known-safe platform selector): the failure
    # JSON lands in git via BENCH_rNN.json, so tunnel endpoints/credentials
    # that may ride AXON_* values must not be echoed
    info: dict = {
        "env_keys": sorted(k for k in os.environ if "AXON" in k or "JAX" in k),
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
    }
    # pid/age/basename ONLY — full argv can carry tunnel endpoints or tokens
    # (e.g. `python -m tunnel --token=...`) and this JSON is committed to git
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,etime,comm"], capture_output=True, text=True, timeout=10
        ).stdout
        info["python_procs"] = [
            " ".join(line.split()[:3])
            for line in out.splitlines()[1:]
            if "python" in line
        ][:20]
    except Exception as e:
        info["python_procs"] = [f"ps failed: {e}"]
    try:
        out = subprocess.run(["ss", "-tln"], capture_output=True, text=True, timeout=10).stdout
        info["listen_ports"] = sorted(
            {
                line.split()[3].rsplit(":", 1)[-1]
                for line in out.splitlines()[1:]
                if len(line.split()) > 3
            }
        )[:10]
    except Exception:
        pass
    return info


def _preflight() -> None:
    # PRIME_BENCH_NO_SWEEP: the watcher's opportunistic bench sets this —
    # its probe just confirmed the tunnel is UP, so there are no stray
    # holders to clear, and sweeping would race the DRIVER's authoritative
    # bench (whichever swept last would SIGKILL the other mid-run)
    no_sweep = bool(os.environ.get("PRIME_BENCH_NO_SWEEP"))
    # Provisional abort record FIRST, before anything that can hang or be
    # killed: the driver takes the LAST JSON line on stdout, so a later
    # success (or the structured abort below) overwrites this — but an
    # external kill at ANY point now leaves a parseable record instead of
    # round 3's parsed:null.
    print(
        json.dumps(
            {
                "metric": "decode_tokens_per_sec (bench killed before preflight verdict)",
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": "provisional record: process was killed before the "
                "preflight finished; see # bench: lines above for progress",
                "backend": os.environ.get("JAX_PLATFORMS", "unknown"),
            }
        ),
        flush=True,
    )
    swept = [] if no_sweep else _sweep_stray_holders()
    if swept:
        print(f"# bench: swept {len(swept)} stray TPU helper(s): {swept}", flush=True)
    errors: list[str] = []
    for attempt in range(len(PROBE_WAITS_S) + 1):
        t0 = time.monotonic()
        reason = _probe_once(PROBE_TIMEOUT_S)
        if reason is None:
            print(
                f"# bench: preflight ok in {time.monotonic() - t0:.0f}s"
                + (f" after {len(errors)} failed probe(s)" if errors else ""),
                flush=True,
            )
            return
        errors.append(reason)
        print(
            f"# bench: preflight probe {attempt + 1}/{len(PROBE_WAITS_S) + 1} failed: {reason}",
            flush=True,
        )
        if attempt < len(PROBE_WAITS_S):
            time.sleep(PROBE_WAITS_S[attempt])
    print(
        json.dumps(
            {
                "metric": "decode_tokens_per_sec (bench aborted)",
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"{len(errors)} probes failed: {errors[-1]}",
                "diagnosis": _diagnose(),
                # NOTE: not jax.default_backend() — that query can hang on
                # the same stuck backend this preflight is detecting
                "backend": os.environ.get("JAX_PLATFORMS", "unknown"),
            }
        ),
        flush=True,  # os._exit below skips the stdio flush
    )
    # os._exit: a hung PJRT client can block normal interpreter teardown
    os._exit(1)


def main() -> None:
    _preflight()
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.models.sampler import generate

    config = get_config(MODEL)
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, config, dtype=jnp.bfloat16)
    prompts = jax.random.randint(jax.random.PRNGKey(1), (BATCH, PROMPT_LEN), 1, config.vocab_size)
    lengths = jnp.full((BATCH,), PROMPT_LEN, dtype=jnp.int32)

    def time_fn(fn, iterations: int = 3) -> float:
        """Best wall-clock seconds over `iterations` (after one warmup/compile
        call). fn must end with a scalar host fetch: on tunneled backends
        (axon) block_until_ready returns before the computation has run."""
        fn()  # warmup + compile
        best_s = float("inf")
        for _ in range(iterations):
            t0 = time.perf_counter()
            fn()
            best_s = min(best_s, time.perf_counter() - t0)
        return best_s

    def run_generate(**kw):
        result = generate(
            params, prompts, lengths, config, jax.random.PRNGKey(2),
            max_new_tokens=NEW_TOKENS, temperature=0.0, **kw,
        )
        float(jnp.sum(result.tokens))

    # ---- headline ------------------------------------------------------------
    best = time_fn(run_generate)
    decode_tok_s = BATCH * NEW_TOKENS / best
    record = {
        "metric": f"decode_tokens_per_sec ({MODEL} bf16, b{BATCH}, p{PROMPT_LEN}+{NEW_TOKENS})",
        "value": round(decode_tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(decode_tok_s / PREV_DECODE_TOK_S, 3),
        "gen_time_s": round(best, 3),
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }
    # early print: an external kill mid-extras still leaves a nonzero record
    print(json.dumps(record), flush=True)

    # ---- eval: the north-star metric through the REAL runner ----------------
    # EvalRunner end to end: tokenizer encode, batch assembly (+ SPMD padding),
    # sharded generate on a 1-device mesh, scoring, results.jsonl writes —
    # the BASELINE.json "verifiers eval samples/sec" definition, not a proxy.
    try:
        import tempfile

        from prime_tpu.evals.runner import EvalRunSpec, JaxGenerator, run_eval

        eval_gen = JaxGenerator(MODEL, slice_name="v5e-1")
        with tempfile.TemporaryDirectory() as td:
            spec = EvalRunSpec(
                env="synthetic-arith",
                model=MODEL,
                limit=32,
                batch_size=8,
                max_new_tokens=64,
                output_dir=td,
            )
            run_eval(spec, generator=eval_gen)  # warmup: compile + first batch shapes
            result = run_eval(spec, generator=eval_gen)
        record["eval_samples_per_sec"] = round(result.metrics["samples_per_sec"], 2)
        record["eval_wall_time_s"] = round(result.metrics["wall_time_s"], 2)
        print(f"# bench: eval {record['eval_samples_per_sec']} samples/s", flush=True)
        del eval_gen
    except Exception as e:  # noqa: BLE001 — a failed extra must not zero the headline
        record["eval_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: eval section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- serve: continuous-batching engine under concurrent load ------------
    n_req, req_new = 16, 64
    serve_prompts = [
        [1] + [(7 * (i + j)) % 1000 + 3 for j in range(96)] for i in range(n_req)
    ]

    def run_serve(
        kv_quant: bool = False, speculative: bool = False, prompts=None
    ) -> float:
        from prime_tpu.serve.engine import ContinuousBatchingEngine

        prompts = prompts or serve_prompts
        engine = ContinuousBatchingEngine(
            params, config, pad_id=0, max_slots=8, capacity=1024, chunk=8,
            kv_quant=kv_quant, speculative=speculative,
        )
        try:
            # warmup: compile prefill/decode/finalize for the buckets in play
            warm = engine.submit(prompts[0], max_new_tokens=req_new)
            while not warm.done:
                engine.tick()
            t0 = time.perf_counter()
            reqs = [engine.submit(ids, max_new_tokens=req_new) for ids in prompts]
            while not all(r.done for r in reqs):
                engine.tick()
            elapsed = time.perf_counter() - t0
            total = sum(len(r.all_tokens(timeout=1)) for r in reqs)
            return total / elapsed
        finally:
            del engine

    # separate guards: an int8 failure must not mark the bf16 number failed
    try:
        record["serve_tok_s"] = round(run_serve(kv_quant=False), 1)
        record["serve_requests"] = n_req
        print(f"# bench: serve {record['serve_tok_s']} tok/s ({n_req} reqs)", flush=True)
    except Exception as e:  # noqa: BLE001
        record["serve_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins
    try:
        # int8-cache engine: same load, half the KV HBM traffic per step
        record["serve_int8_tok_s"] = round(run_serve(kv_quant=True), 1)
        print(f"# bench: serve int8 {record['serve_int8_tok_s']} tok/s", flush=True)
    except Exception as e:  # noqa: BLE001
        record["serve_int8_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve int8 section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins
    try:
        # speculative engine on genuinely PERIODIC prompts (the favorable
        # regime: continuations repeat the cycle, so n-gram drafts land and
        # each verify pass emits several tokens) — the default serve_prompts
        # are an arithmetic progression with no repeated bigrams
        periodic = [
            [1] + list(range(3 + i, 11 + i)) * 12 for i in range(n_req)
        ]
        record["serve_spec_tok_s"] = round(
            run_serve(speculative=True, prompts=periodic), 1
        )
        print(f"# bench: serve speculative {record['serve_spec_tok_s']} tok/s", flush=True)
    except Exception as e:  # noqa: BLE001
        record["serve_spec_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: serve speculative section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- quant: int8 weights / int8 KV --------------------------------------
    try:
        from prime_tpu.models.quantize import quantize_params_int8

        qparams = quantize_params_int8(params)

        def run_q(kv_quant=False):
            # auto dispatch: int8 caches ride the flash kernel at long
            # context now (round 4); short-context headline stays XLA
            result = generate(
                qparams, prompts, lengths, config, jax.random.PRNGKey(2),
                max_new_tokens=NEW_TOKENS, temperature=0.0,
                **({"kv_quant": True} if kv_quant else {}),
            )
            float(jnp.sum(result.tokens))

        record["int8_weights_tok_s"] = round(BATCH * NEW_TOKENS / time_fn(run_q), 1)
        record["int8_weights_kv_tok_s"] = round(
            BATCH * NEW_TOKENS / time_fn(lambda: run_q(kv_quant=True)), 1
        )
        print(f"# bench: int8 weights {record['int8_weights_tok_s']} tok/s", flush=True)
    except Exception as e:  # noqa: BLE001
        record["quant_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: quant section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- longctx: flash-decode pallas kernel vs XLA at C=4096 ---------------
    # The regime the kernel exists for (short context dispatches to XLA via
    # PRIME_TPU_FLASH_DECODE_MIN_C). VERDICT r2 #5: prove it or retire it.
    try:
        lc_batch, lc_prompt, lc_new = 4, 3968, 64
        lc_prompts = jax.random.randint(
            jax.random.PRNGKey(3), (lc_batch, lc_prompt), 1, config.vocab_size
        )

        def run_lc(impl):
            result = generate(
                params,
                lc_prompts,
                jnp.full((lc_batch,), lc_prompt, dtype=jnp.int32),
                config,
                jax.random.PRNGKey(2),
                max_new_tokens=lc_new,
                temperature=0.0,
                attn_impl=impl,
            )
            float(jnp.sum(result.tokens))

        xla_s = time_fn(lambda: run_lc("xla"), iterations=2)
        pallas_s = time_fn(lambda: run_lc("pallas"), iterations=2)
        record["longctx_xla_tok_s"] = round(lc_batch * lc_new / xla_s, 1)
        record["longctx_pallas_tok_s"] = round(lc_batch * lc_new / pallas_s, 1)
        record["longctx_pallas_speedup"] = round(xla_s / pallas_s, 3)
        print(
            f"# bench: longctx C=4096 pallas {record['longctx_pallas_tok_s']} vs "
            f"xla {record['longctx_xla_tok_s']} tok/s",
            flush=True,
        )
        # int8-KV at long context: the round-4 kernel streams half the cache
        # bytes with scales folded — the regime the variant exists for
        def run_lc_q(impl):
            result = generate(
                params,
                lc_prompts,
                jnp.full((lc_batch,), lc_prompt, dtype=jnp.int32),
                config,
                jax.random.PRNGKey(2),
                max_new_tokens=lc_new,
                temperature=0.0,
                attn_impl=impl,
                kv_quant=True,
            )
            float(jnp.sum(result.tokens))

        q_xla_s = time_fn(lambda: run_lc_q("xla"), iterations=2)
        q_pallas_s = time_fn(lambda: run_lc_q("pallas"), iterations=2)
        record["longctx_int8kv_xla_tok_s"] = round(lc_batch * lc_new / q_xla_s, 1)
        record["longctx_int8kv_pallas_tok_s"] = round(lc_batch * lc_new / q_pallas_s, 1)
        record["longctx_int8kv_pallas_speedup"] = round(q_xla_s / q_pallas_s, 3)
        print(
            f"# bench: longctx int8-KV pallas {record['longctx_int8kv_pallas_tok_s']} vs "
            f"xla {record['longctx_int8kv_xla_tok_s']} tok/s",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["longctx_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: longctx section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- winctx: sliding-window flash decode at long context ----------------
    # The round-4 kernel variant: a sliding layer's decode step front-skips
    # cache blocks before the window, so it streams ~window slots instead of
    # the whole cache (Gemma2/3, Mistral, GPT-OSS layers). Microbench of the
    # decode step itself at C=4096 / window=1024: pallas-with-skip vs the
    # XLA path that reads everything and masks.
    try:
        from prime_tpu.ops.attention import decode_attention

        wb, wh, wkh, wd, wc, wwin = 8, 32, 8, 64, 4096, 1024
        wq = jax.random.normal(jax.random.PRNGKey(7), (wb, wh, 1, wd), dtype=jnp.bfloat16)
        wk = jax.random.normal(jax.random.PRNGKey(8), (wb, wkh, wd, wc), dtype=jnp.bfloat16)
        wv = jax.random.normal(jax.random.PRNGKey(9), (wb, wkh, wd, wc), dtype=jnp.bfloat16)
        wlens = jnp.full((wb,), wc, dtype=jnp.int32)

        # both sides jitted: an eager XLA baseline would pay per-op dispatch
        # at this microsecond scale and flatter the kernel (spdecode's scheme)
        win_xla_fn = jax.jit(
            lambda: decode_attention(
                wq, wk, wv, wlens, wd**-0.5, impl="xla", window=wwin,
                sliding=jnp.asarray(True),
            )
        )
        win_pallas_fn = jax.jit(
            lambda: decode_attention(
                wq, wk, wv, wlens, wd**-0.5, impl="pallas", window=wwin,
                sliding=jnp.asarray(True),
            )
        )
        win_xla_s = time_fn(lambda: float(jnp.sum(win_xla_fn())), iterations=5)
        win_pallas_s = time_fn(lambda: float(jnp.sum(win_pallas_fn())), iterations=5)
        record["winctx_xla_us"] = round(win_xla_s * 1e6, 1)
        record["winctx_pallas_us"] = round(win_pallas_s * 1e6, 1)
        record["winctx_pallas_speedup"] = round(win_xla_s / win_pallas_s, 3)
        print(
            f"# bench: winctx C={wc} win={wwin} pallas {record['winctx_pallas_us']}us "
            f"vs xla {record['winctx_xla_us']}us",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["winctx_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: winctx section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # ---- spdecode: sequence-parallel decode step ----------------------------
    # The long-context decode path a v5e-8+ slice runs (cache slots sharded
    # over sp, two-phase softmax combine — parallel/long_context.py), timed
    # through the IDENTICAL shard_map code on the bench chip's sp=1 mesh.
    # What a single chip can measure is the sp machinery's overhead vs the
    # plain decode step (expect ~1.0x); cross-chip scaling needs a slice the
    # driver doesn't have. Parity at sp=8 is locked by
    # tests/test_parallel.py::test_sp_decode_parity_long_cache.
    try:
        from prime_tpu.ops.attention import decode_attention
        from prime_tpu.parallel.long_context import sp_decode_attention
        from prime_tpu.parallel.mesh import make_mesh

        sp_b, sp_h, sp_kh, sp_d, sp_c = 8, 32, 8, 64, 4096
        sp_q = jax.random.normal(jax.random.PRNGKey(4), (sp_b, sp_h, 1, sp_d), dtype=jnp.bfloat16)
        sp_k = jax.random.normal(jax.random.PRNGKey(5), (sp_b, sp_kh, sp_d, sp_c), dtype=jnp.bfloat16)
        sp_v = jax.random.normal(jax.random.PRNGKey(6), (sp_b, sp_kh, sp_d, sp_c), dtype=jnp.bfloat16)
        sp_lens = jnp.full((sp_b,), sp_c, dtype=jnp.int32)
        mesh1 = make_mesh({"sp": 1})
        plain_fn = jax.jit(
            lambda: decode_attention(sp_q, sp_k, sp_v, sp_lens, sp_d**-0.5, impl="xla")
        )
        sp_fn = jax.jit(lambda: sp_decode_attention(sp_q, sp_k, sp_v, sp_lens, mesh1))
        plain_s = time_fn(lambda: float(jnp.sum(plain_fn())), iterations=5)
        sp_s = time_fn(lambda: float(jnp.sum(sp_fn())), iterations=5)
        record["spdecode_plain_us"] = round(plain_s * 1e6, 1)
        record["spdecode_sp_us"] = round(sp_s * 1e6, 1)
        record["spdecode_overhead"] = round(sp_s / plain_s, 3)
        print(
            f"# bench: spdecode C={sp_c} sp-path {record['spdecode_sp_us']}us vs "
            f"plain {record['spdecode_plain_us']}us",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        record["spdecode_error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# bench: spdecode section failed: {e}", flush=True)
    print(json.dumps(record), flush=True)  # checkpoint: last JSON line wins

    # final, enriched record — last JSON line on stdout wins
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
