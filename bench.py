"""Benchmark: autoregressive generation throughput on the real TPU chip.

Proxy for the north-star workload (gsm8k eval samples/sec, BASELINE.md): the
eval runner's cost is dominated by batched prefill + greedy decode, which is
exactly what this measures — llama3.2-1b architecture (random weights;
throughput is weight-value independent), bf16, batch 8, 128-token prompts,
128 new tokens.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
The reference publishes no numbers (BASELINE.json "published": {}), so
vs_baseline is the ratio against PREV_DECODE_TOK_S below — the first recorded
round of this repo; update it when the bench materially improves.
"""

import json
import time

import jax
import jax.numpy as jnp

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.models.sampler import generate

# Round-1 anchor (v5e-1, this repo @ first bench). vs_baseline = value / this.
PREV_DECODE_TOK_S = 1396.6

BATCH = 8
PROMPT_LEN = 128
NEW_TOKENS = 128
MODEL = "llama3.2-1b"


def _probe_once(timeout_s: float) -> str | None:
    """One accelerator probe in a SUBPROCESS (fresh PJRT client — an in-process
    retry would reuse the same stuck client). None on success, else a reason."""
    import subprocess
    import sys

    code = (
        "import jax, jax.numpy as jnp\n"
        "x = jnp.ones((256, 256))\n"
        "print(float(jnp.sum(x @ x)))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=timeout_s
        )
    except subprocess.TimeoutExpired:
        return f"backend unresponsive after {timeout_s:.0f}s"
    if proc.returncode != 0:
        return f"probe rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    return None


def _preflight(attempts: int = 4, timeout_s: float = 120.0, wait_s: float = 60.0) -> None:
    """The tunneled TPU occasionally stalls *transiently* — retry the probe a
    few times (~10 min budget) before giving up with a clean JSON diagnostic.
    Round 1 aborted on the first failed probe and recorded a 0.0 bench."""
    errors: list[str] = []
    for attempt in range(attempts):
        reason = _probe_once(timeout_s)
        if reason is None:
            if errors:
                print(f"# preflight recovered after {len(errors)} failed probe(s)", flush=True)
            return
        errors.append(reason)
        print(f"# preflight probe {attempt + 1}/{attempts} failed: {reason}", flush=True)
        if attempt < attempts - 1:
            time.sleep(wait_s)
    import os

    print(
        json.dumps(
            {
                "metric": "decode_tokens_per_sec (bench aborted)",
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"{attempts} probes failed: {errors[-1]}",
                # NOTE: not jax.default_backend() — that query can hang on
                # the same stuck backend this preflight is detecting
                "backend": os.environ.get("JAX_PLATFORMS", "unknown"),
            }
        ),
        flush=True,  # os._exit below skips the stdio flush
    )
    # os._exit: a hung PJRT client can block normal interpreter teardown
    os._exit(1)


def main() -> None:
    _preflight()
    config = get_config(MODEL)
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, config, dtype=jnp.bfloat16)
    prompts = jax.random.randint(jax.random.PRNGKey(1), (BATCH, PROMPT_LEN), 1, config.vocab_size)
    lengths = jnp.full((BATCH,), PROMPT_LEN, dtype=jnp.int32)

    def time_fn(fn, iterations: int = 3) -> float:
        """Best wall-clock seconds over `iterations` (after one warmup/compile
        call). fn must end with a scalar host fetch: on tunneled backends
        (axon) block_until_ready returns before the computation has run."""
        fn()  # warmup + compile
        best_s = float("inf")
        for _ in range(iterations):
            t0 = time.perf_counter()
            fn()
            best_s = min(best_s, time.perf_counter() - t0)
        return best_s

    def run_generate(prompt_tokens=None, **kw):
        result = generate(
            params,
            prompts if prompt_tokens is None else prompt_tokens,
            lengths,
            config,
            jax.random.PRNGKey(2),
            max_new_tokens=NEW_TOKENS,
            temperature=0.0,
            **kw,
        )
        float(jnp.sum(result.tokens))

    best = time_fn(run_generate)
    decode_tok_s = BATCH * NEW_TOKENS / best
    samples_per_sec = BATCH / best

    # sharded serve path on a 1-device mesh: same code the eval runner uses
    # with --slice (VERDICT r1 asked for the sharded generate timed on-chip)
    from jax.sharding import NamedSharding

    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import (
        batch_spec,
        cache_spec,
        lengths_spec,
        shard_params,
    )

    mesh = make_mesh({"dp": 1, "fsdp": 1, "tp": 1}, devices=jax.devices()[:1])
    sharded = shard_params(params, mesh, config)
    prompts_s = jax.device_put(prompts, NamedSharding(mesh, batch_spec()))
    lengths_s = jax.device_put(lengths, NamedSharding(mesh, lengths_spec()))

    def run_sharded():
        with jax.set_mesh(mesh):
            result = generate(
                sharded,
                prompts_s,
                lengths_s,
                config,
                jax.random.PRNGKey(2),
                max_new_tokens=NEW_TOKENS,
                temperature=0.0,
                cache_spec=cache_spec(),
            )
        float(jnp.sum(result.tokens))

    sharded_tok_s = BATCH * NEW_TOKENS / time_fn(run_sharded)

    # int8 KV cache vs the SAME (XLA) decode path: the quantized cache has no
    # pallas kernel yet, so compare against an XLA fp run — otherwise the
    # kernel switch, not quantization, would dominate the delta
    xla_fp_tok_s = BATCH * NEW_TOKENS / time_fn(lambda: run_generate(attn_impl="xla"))
    q8_tok_s = BATCH * NEW_TOKENS / time_fn(
        lambda: run_generate(attn_impl="xla", kv_quant=True)
    )

    # W8A16: int8 weights halve the dominant decode bytes at small batch
    from prime_tpu.models.quantize import quantize_params_int8

    qparams = quantize_params_int8(params)

    def run_w8():
        result = generate(
            qparams,
            prompts,
            lengths,
            config,
            jax.random.PRNGKey(2),
            max_new_tokens=NEW_TOKENS,
            temperature=0.0,
        )
        float(jnp.sum(result.tokens))

    w8_tok_s = BATCH * NEW_TOKENS / time_fn(run_w8)
    def run_w8_q8():
        result = generate(
            qparams,
            prompts,
            lengths,
            config,
            jax.random.PRNGKey(2),
            max_new_tokens=NEW_TOKENS,
            temperature=0.0,
            attn_impl="xla",
            kv_quant=True,
        )
        float(jnp.sum(result.tokens))

    w8_q8_tok_s = BATCH * NEW_TOKENS / time_fn(run_w8_q8)

    # prompt-lookup speculative decoding on periodic context (the favorable
    # case: drafts accept). Secondary metric — the headline stays plain bf16.
    from prime_tpu.models.speculative import spec_generate

    periodic = jnp.tile(jnp.arange(1, 17, dtype=jnp.int32), (BATCH, PROMPT_LEN // 16))

    def run_spec():
        result = spec_generate(
            params, periodic, lengths, config, max_new_tokens=NEW_TOKENS, draft_len=4
        )
        float(jnp.sum(result.tokens))

    spec_tok_s = BATCH * NEW_TOKENS / time_fn(run_spec)
    plain_periodic_tok_s = BATCH * NEW_TOKENS / time_fn(
        lambda: run_generate(prompt_tokens=periodic)
    )

    print(
        json.dumps(
            {
                "metric": f"decode_tokens_per_sec ({MODEL} bf16, b{BATCH}, p{PROMPT_LEN}+{NEW_TOKENS})",
                "value": round(decode_tok_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(decode_tok_s / PREV_DECODE_TOK_S, 3),
                "samples_per_sec": round(samples_per_sec, 2),
                "gen_time_s": round(best, 3),
                "sharded_1dev_tok_s": round(sharded_tok_s, 1),
                "xla_fp_tok_s": round(xla_fp_tok_s, 1),
                "int8_kv_xla_tok_s": round(q8_tok_s, 1),
                "int8_weights_tok_s": round(w8_tok_s, 1),
                "int8_weights_kv_tok_s": round(w8_q8_tok_s, 1),
                "spec_periodic_tok_s": round(spec_tok_s, 1),
                "plain_periodic_tok_s": round(plain_periodic_tok_s, 1),
                "backend": jax.default_backend(),
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
