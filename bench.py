"""Benchmark: autoregressive generation throughput on the real TPU chip.

Proxy for the north-star workload (gsm8k eval samples/sec, BASELINE.md): the
eval runner's cost is dominated by batched prefill + greedy decode, which is
exactly what this measures — llama3.2-1b architecture (random weights;
throughput is weight-value independent), bf16, batch 8, 128-token prompts,
128 new tokens.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
The reference publishes no numbers (BASELINE.json "published": {}), so
vs_baseline is the ratio against PREV_DECODE_TOK_S below — the first recorded
round of this repo; update it when the bench materially improves.
"""

import json
import time

import jax
import jax.numpy as jnp

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.models.sampler import generate

# Round-1 anchor (v5e-1, this repo @ first bench). vs_baseline = value / this.
PREV_DECODE_TOK_S = 1396.6

BATCH = 8
PROMPT_LEN = 128
NEW_TOKENS = 128
MODEL = "llama3.2-1b"


def _preflight(timeout_s: float = 180.0) -> None:
    """Fail fast (clean JSON diagnostic) if the accelerator backend is hung —
    the tunneled TPU occasionally stalls; a hang here would block the driver."""
    import threading

    done = threading.Event()
    error: list[str] = []

    def probe() -> None:
        try:
            x = jnp.ones((64, 64))
            float(jnp.sum(x @ x))
            done.set()
        except Exception as e:  # pragma: no cover
            error.append(str(e))
            done.set()

    thread = threading.Thread(target=probe, daemon=True)
    thread.start()
    if not done.wait(timeout_s) or error:
        import os

        reason = error[0] if error else f"backend unresponsive after {timeout_s:.0f}s"
        print(
            json.dumps(
                {
                    "metric": "decode_tokens_per_sec (bench aborted)",
                    "value": 0.0,
                    "unit": "tokens/s",
                    "vs_baseline": 0.0,
                    "error": reason,
                    # NOTE: not jax.default_backend() — that query can hang on
                    # the same stuck backend this preflight is detecting
                    "backend": os.environ.get("JAX_PLATFORMS", "unknown"),
                }
            ),
            flush=True,  # os._exit below skips the stdio flush
        )
        # os._exit: a hung PJRT client can block normal interpreter teardown
        os._exit(1)


def main() -> None:
    _preflight()
    config = get_config(MODEL)
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, config, dtype=jnp.bfloat16)
    prompts = jax.random.randint(jax.random.PRNGKey(1), (BATCH, PROMPT_LEN), 1, config.vocab_size)
    lengths = jnp.full((BATCH,), PROMPT_LEN, dtype=jnp.int32)

    def run():
        result = generate(
            params,
            prompts,
            lengths,
            config,
            jax.random.PRNGKey(2),
            max_new_tokens=NEW_TOKENS,
            temperature=0.0,
        )
        # fetch a scalar to force execution: on tunneled backends (axon)
        # block_until_ready returns before the computation has run
        float(jnp.sum(result.tokens))
        return result

    run()  # warmup + compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    best = min(times)
    decode_tok_s = BATCH * NEW_TOKENS / best
    samples_per_sec = BATCH / best

    print(
        json.dumps(
            {
                "metric": f"decode_tokens_per_sec ({MODEL} bf16, b{BATCH}, p{PROMPT_LEN}+{NEW_TOKENS})",
                "value": round(decode_tok_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(decode_tok_s / PREV_DECODE_TOK_S, 3),
                "samples_per_sec": round(samples_per_sec, 2),
                "gen_time_s": round(best, 3),
                "backend": jax.default_backend(),
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
